"""SPMD execution: run a traced Program block under jax.shard_map over a Mesh.

This is the GSPMD replacement for the reference's ParallelExecutor SSA-graph
runtime (parallel_executor.cc:443): instead of cloning the graph per device
and scheduling op handles across threads/streams
(details/fast_threaded_ssa_graph_executor.cc:54), ONE program runs on every
shard; collective ops (ops/collective.py) see the mesh axis names and emit
ICI collectives; everything else is element-local and XLA partitions it.

Sharding metadata lives on the Program: `program._sharding` maps var name ->
tuple of mesh-axis names per dimension (None entries = replicated dim), the
moral equivalent of GSPMD sharding annotations. Unlisted vars are replicated
— the reference's default of broadcasting parameters to every device
(parallel_executor.cc:570 BCastParamsToDevices) without any copy loop.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def _shard_map(f, mesh, in_specs, out_specs, check_vma=False,
               axis_names=None):
    """Version-compat shard_map: new-style jax.shard_map (check_vma /
    axis_names) when present, else jax.experimental.shard_map.shard_map
    (check_rep, and `auto` = the COMPLEMENT of axis_names)."""
    if hasattr(jax, "shard_map"):
        kw = {"axis_names": axis_names} if axis_names is not None else {}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kw,
    )


def spec_for(program, name) -> P:
    s = program._sharding.get(name)
    if not s:
        # same-shaped optimizer accumulators INHERIT their parameter's
        # spec (optimizer.py tags them with _accum_of). A TP/stage-sharded
        # weight must not drag a spec-less moment through its elementwise
        # update: inside a manual shard_map body the param arrives sliced
        # while the moment arrives full, and the update silently
        # broadcasts. Accumulator names carry a unique_name suffix, so
        # spec-by-name from the user cannot be relied on.
        v = program.global_block._find_var_recursive(name)
        parent = getattr(v, "_accum_of", None)
        if parent is not None and parent != name:
            pv = program.global_block._find_var_recursive(parent)
            if (
                pv is not None
                and tuple(v.shape or ()) == tuple(pv.shape or ())
            ):
                return spec_for(program, parent)
        return P()
    return P(*s)


def _spans_processes(mesh):
    """True when the mesh includes devices of other processes (multi-host
    SPMD: every participating process runs the same program)."""
    return any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )


def stage_global(x, mesh, pspec, multiproc=None, local_is_full=False):
    """Make `x` a global array on the mesh.

    Single-process: plain device_put. Multi-process: assemble the global
    view with jax.make_array_from_process_local_data — the TPU-native
    replacement for the reference's per-trainer feed +
    BCastParamsToDevices bootstrap. Two local-data conventions:
      * feeds (local_is_full=False): each process holds only ITS shard
        (dp input pipeline), global shape is inferred by concatenation;
      * state (local_is_full=True): each process holds the FULL value
        (startup ran locally); global_shape=x.shape makes
        make_array_from_process_local_data slice out this process's part —
        required for cross-process-sharded state like ps tables.
    """
    import numpy as np

    sharding = NamedSharding(mesh, pspec)
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return x  # already a global array (e.g. written-back state)
    if multiproc is None:
        multiproc = _spans_processes(mesh)
    if multiproc:
        arr = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, arr, global_shape=arr.shape if local_is_full else None
        )
    return jax.device_put(x, sharding)


def _project_spec(spec, manual):
    """Drop non-manual axis names from a PartitionSpec (hybrid mode: the
    shard_map body is manual over `manual` only; other mesh axes are Auto —
    their sharding rides on the arrays' NamedShardings and XLA propagation,
    exactly gspmd, while manual axes keep explicit collectives)."""
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a in manual)
            out.append(kept if kept else None)
        else:
            out.append(e if e in manual else None)
    return P(*out)


def wrap_shard_map(
    traced, program, mesh, state_ro, state_mut, write_back, fetch_names,
    manual_axes=None,
):
    """Wrap the executor's traced block for SPMD execution.

    traced(feeds, smut, sro, step_key) -> (tuple_of_fetches, new_state_dict)
    with static structure: new_state keys == write_back exactly.

    manual_axes: None = fully manual (classic shard_map). A subset of mesh
    axis names = HYBRID mode: the body is manual over those axes (explicit
    collective ops, lax.axis_index — what the pipeline scheduler needs)
    while the remaining axes are Auto — arrays stay global over them and
    the XLA SPMD partitioner shards per annotation, which is how Megatron
    tensor parallelism composes with the pipeline in ONE program. The
    reference could not express this mix (every strategy was a separate
    NCCL transpile); on TPU it is one jit.
    """
    manual = (
        frozenset(manual_axes) if manual_axes is not None
        else frozenset(mesh.axis_names)
    )
    partial_manual = manual != frozenset(mesh.axis_names)

    def body_spec(name):
        s = spec_for(program, name)
        return _project_spec(s, manual) if partial_manual else s

    def run(feeds, smut, sro, step_key):
        in_specs = (
            {k: body_spec(k) for k in feeds},
            {k: body_spec(k) for k in smut},
            {k: body_spec(k) for k in sro},
            P(),
        )
        out_specs = (
            tuple(body_spec(n) for n in fetch_names),
            {n: body_spec(n) for n in write_back},
        )
        sm = _shard_map(
            traced,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
            axis_names=manual if partial_manual else None,
        )
        return sm(feeds, smut, sro, step_key)

    jitted = jax.jit(run, donate_argnums=(1,))
    multiproc = _spans_processes(mesh)
    from .. import observability as _obs

    _obs.set_gauge("collective.mesh_devices", mesh.size)

    mesh_desc = "x".join(f"{k}{v}" for k, v in mesh.shape.items())

    def fn(feeds, smut, sro, step_key):
        _obs.add("collective.shard_map_dispatches")
        # a traced child span under executor.step: in a causal trace the
        # staging+dispatch segment is attributable to the mesh, and the
        # mesh shape rides on the span for the pod-timeline merge
        with _obs.span("spmd.dispatch", category="spmd", mesh=mesh_desc):
            feeds = {
                k: stage_global(v, mesh, spec_for(program, k), multiproc)
                for k, v in feeds.items()
            }
            if multiproc or partial_manual:
                # multi-process: state must be global arrays; each
                # process's scope holds the FULL value (startup ran
                # locally), so local_is_full slices out this process's
                # part.
                # hybrid: the Auto axes' sharding lives ONLY on the
                # arrays' committed NamedShardings (the body specs
                # project them away), so state must be staged with its
                # full spec or mp-annotated params silently stay
                # replicated on every device
                smut = {
                    k: stage_global(
                        v, mesh, spec_for(program, k), multiproc,
                        local_is_full=True,
                    )
                    for k, v in smut.items()
                }
                sro = {
                    k: stage_global(
                        v, mesh, spec_for(program, k), multiproc,
                        local_is_full=True,
                    )
                    for k, v in sro.items()
                }
            return jitted(feeds, smut, sro, step_key)

    return fn


def wrap_gspmd(
    traced, program, mesh, state_ro, state_mut, write_back, fetch_names,
    manual_axes=None,
):
    """GSPMD mode: no explicit collectives, no shard_map. Inputs are committed
    to the mesh per their annotations; jax.jit + the XLA SPMD partitioner
    propagate shardings through the whole block and insert ICI collectives
    where the dataflow demands them (e.g. the psum after a row-parallel
    matmul in tensor parallelism). This is the design the reference could
    never reach with NCCL op handles: sharding is declared, not programmed.
    """

    jitted = jax.jit(traced, donate_argnums=(1,))
    multiproc = _spans_processes(mesh)
    from .. import observability as _obs

    _obs.set_gauge("collective.mesh_devices", mesh.size)

    def put(k, v):
        # multi-process gspmd convention: every process holds the FULL
        # value (feeds are replicated inputs, state came from a local
        # startup run) — stage_global(local_is_full=True) slices out this
        # process's addressable part and assembles the global array
        return stage_global(
            v, mesh, spec_for(program, k), multiproc, local_is_full=True
        )

    mesh_desc = "x".join(f"{k}{v}" for k, v in mesh.shape.items())

    def fn(feeds, smut, sro, step_key):
        _obs.add("collective.gspmd_dispatches")
        with _obs.span("spmd.dispatch", category="spmd", mesh=mesh_desc):
            feeds = {k: put(k, v) for k, v in feeds.items()}
            smut = {k: put(k, v) for k, v in smut.items()}
            sro = {k: put(k, v) for k, v in sro.items()}
            return jitted(feeds, smut, sro, step_key)

    return fn


def device_put_sharded(x, mesh, pspec):
    """Commit a host array onto the mesh with the given PartitionSpec."""
    return jax.device_put(x, NamedSharding(mesh, pspec))


def shard_program(program, mesh, shardings=None, mode="shard_map",
                  manual_axes=None):
    """Attach a mesh + sharding annotations to a Program (SPMD mode switch).

    shardings: {var_name: tuple_of_axis_names_per_dim}. E.g. a data-parallel
    feed image of rank 4 -> {"image": ("dp", None, None, None)} (in practice
    only leading axes need naming: ("dp",) suffices as a prefix spec).

    mode: "shard_map" (explicit collective ops, fleet/transpiled programs),
    "gspmd" (annotation-only, XLA-propagated — use for tensor parallelism),
    or "hybrid" (manual_axes are shard_map-manual with explicit collectives,
    every other mesh axis is gspmd-Auto — composes pipeline/dp collectives
    with tensor-parallel annotation propagation in one program).
    """
    program._mesh = mesh
    program._spmd_mode = mode
    if mode == "hybrid":
        if not manual_axes:
            raise ValueError("hybrid mode requires manual_axes")
        unknown = set(manual_axes) - set(mesh.axis_names)
        if unknown:
            raise ValueError(
                f"manual_axes {sorted(unknown)} not in mesh axes "
                f"{mesh.axis_names}"
            )
        program._manual_axes = tuple(manual_axes)
    if shardings:
        program._sharding.update(
            {k: tuple(v) for k, v in shardings.items()}
        )
    program._bump()
    return program
