"""Sequence/context parallelism: ring attention and Ulysses.

The reference has NO long-context parallelism (SURVEY.md §5: "absent") —
this is greenfield, designed TPU-first:

* Ring attention: the sequence axis is sharded over the "sp" mesh axis;
  each device keeps its Q shard resident and K/V shards rotate around the
  ring via lax.ppermute (ICI neighbor exchange), overlapping the blockwise
  attention compute of step i with the transfer of step i+1 (XLA's
  latency-hiding scheduler pipelines the ppermute against the matmuls).
  Softmax is computed online (running max/denominator), so no S×S matrix
  ever materializes — O(S_local × S_block) memory.

* Ulysses: all_to_all reshard from sequence-sharded to head-sharded,
  full local attention, all_to_all back. One pair of all_to_alls per layer
  vs n_ring ppermutes; better when heads ≥ mesh axis size.

Both are differentiable through the generic vjp path (ppermute/all_to_all
transpose to their inverses under jax.vjp).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


_NEG = -1e30  # finite stand-in for -inf: keeps exp() NaN-free on fully
# masked blocks (a ring step where every key is causally ahead of this query
# shard) — p is zeroed through `valid` instead of relying on exp(-inf)


def _online_block(q, k, v, valid, m, l, acc, scale):
    """One blockwise-attention accumulation step (online softmax).

    q [B,H,Sq,D], k/v [B,H,Sk,D], valid broadcastable bool [Sq,Sk] or None,
    m/l running max/denominator [B,H,Sq,1], acc [B,H,Sq,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


# keys processed per online-softmax block: bounds the materialized score
# block to [S_local, _KV_CHUNK] regardless of shard size, so ring
# attention scales to shards far beyond the [S_local, S_local] HBM cliff
# (a 32k shard would otherwise stream multi-GB probability blocks per
# ring step)
_KV_CHUNK = 1024


def _valid_mask(row0, col0, sq, sk):
    rows = row0 + jnp.arange(sq)[:, None]
    cols = col0 + jnp.arange(sk)[None, :]
    return rows >= cols


def _online_shard(qf, kf, vf, row0, col0, causal, m, l, acc, scale):
    """Accumulate one full K/V shard into the running softmax state,
    scanning _KV_CHUNK-sized key blocks (lax.scan) when the shard is
    larger — the in-XLA analog of the Pallas KV tiling, and still
    differentiable through the generic vjp path (scan transposes)."""
    sq = qf.shape[2]
    sk = kf.shape[2]
    if sk <= _KV_CHUNK:
        valid = _valid_mask(row0, col0, sq, sk) if causal else None
        return _online_block(qf, kf, vf, valid, m, l, acc, scale)

    # jax.checkpoint: WITHOUT it the scan's backward saves each chunk's
    # softmax residuals (p et al., [Sq, _KV_CHUNK] stacked over all
    # chunks) — re-materializing the very [Sq, sk]-sized memory the
    # chunking exists to avoid; rematerializing the chunk in the
    # backward is the standard flash-attention trade
    @jax.checkpoint
    def body(carry, i):
        m_, l_, acc_ = carry
        kc = lax.dynamic_slice_in_dim(kf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        vc = lax.dynamic_slice_in_dim(vf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        valid = (
            _valid_mask(row0, col0 + i * _KV_CHUNK, sq, _KV_CHUNK)
            if causal else None
        )
        return _online_block(qf, kc, vc, valid, m_, l_, acc_, scale), None

    chunks = sk // _KV_CHUNK
    (m, l, acc), _ = lax.scan(body, (m, l, acc), jnp.arange(chunks))
    tail = sk - chunks * _KV_CHUNK
    if tail:
        # non-multiple shard: the remainder is ONE small block — never the
        # full [sq, sk] score block (that would reopen the HBM cliff the
        # chunking exists to close)
        kc = kf[:, :, chunks * _KV_CHUNK:]
        vc = vf[:, :, chunks * _KV_CHUNK:]
        valid = (
            _valid_mask(row0, col0 + chunks * _KV_CHUNK, sq, tail)
            if causal else None
        )
        m, l, acc = _online_block(qf, kc, vc, valid, m, l, acc, scale)
    return m, l, acc


def _online_init(b, h, sq, d):
    return (
        jnp.full((b, h, sq, 1), _NEG, dtype=jnp.float32),
        jnp.zeros((b, h, sq, 1), dtype=jnp.float32),
        jnp.zeros((b, h, sq, d), dtype=jnp.float32),
    )


def _online_finalize(l, acc):
    return acc / jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0


def ring_attention(q, k, v, axis_name, axis_size, causal=False, scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D] inside shard_map.

    Returns [B, H, S_local, D]. Rotates K/V around the ring; at step t this
    device (index i) processes the K/V shard originating at (i + t) mod n.
    """
    n = int(axis_size)
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    idx = lax.axis_index(axis_name)

    m, l, acc = _online_init(b, h, s_local, d)
    qf = q.astype(jnp.float32)

    perm = [(i, (i - 1) % n) for i in range(n)]  # send to left neighbor
    kt, vt = k, v
    for t in range(n):
        src = (idx + t) % n  # which shard kt/vt currently holds
        # global positions: rows i*s_local + r, cols src*s_local + c
        m, l, acc = _online_shard(
            qf, kt.astype(jnp.float32), vt.astype(jnp.float32),
            idx * s_local, src * s_local, causal, m, l, acc, scale,
        )
        if t != n - 1:
            kt = lax.ppermute(kt, axis_name, perm)
            vt = lax.ppermute(vt, axis_name, perm)

    return _online_finalize(l, acc).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, axis_size, causal=False, scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D]; H must divide axis_size.

    all_to_all: seq-sharded -> head-sharded, dense local attention over the
    FULL sequence, all_to_all back (head-sharded -> seq-sharded).
    """
    n = int(axis_size)
    b, h, s_local, d = q.shape
    if h % n:
        raise ValueError(f"ulysses: heads {h} not divisible by axis size {n}")

    def to_heads(x):  # [B,H,Sl,D] -> [B,H/n,S,D]
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def to_seq(x):  # [B,H/n,S,D] -> [B,H,Sl,D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    bh, hh, s_full, _ = qh.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # the head-sharded local attention spans the FULL sequence: stream it
    # through the same chunked online softmax as the ring path — a dense
    # [S, S] block at long context is exactly the cliff SP exists to avoid
    m, l, acc = _online_init(bh, hh, s_full, d)
    m, l, acc = _online_shard(
        qh.astype(jnp.float32), kh.astype(jnp.float32),
        vh.astype(jnp.float32), 0, 0, causal, m, l, acc, scale,
    )
    return to_seq(_online_finalize(l, acc).astype(q.dtype))


# ---------------------------------------------------------------------------
# op registrations (static graph)
# ---------------------------------------------------------------------------

from ..framework.registry import register_op  # noqa: E402


def _attention_fallback(q, k, v, causal, scale):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        n = s.shape[-1]
        mask = jnp.arange(n)[:, None] >= jnp.arange(n)[None, :]
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register_op("ring_attention", inputs=["Q", "K", "V"], outputs=["Out"])
def _ring_attention_op(ctx, op, ins):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    axis = op.attr("axis_name", "sp")
    causal = op.attr("causal", False)
    scale = op.attr("scale", None)
    if axis in ctx.mesh_axes:
        out = ring_attention(
            q, k, v, axis, ctx.axis_sizes[axis], causal=causal, scale=scale
        )
    else:  # single-shard: dense attention (nranks==1 degradation)
        out = _attention_fallback(q, k, v, causal, scale)
    return {"Out": [out]}


@register_op("ulysses_attention", inputs=["Q", "K", "V"], outputs=["Out"])
def _ulysses_attention_op(ctx, op, ins):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    axis = op.attr("axis_name", "sp")
    causal = op.attr("causal", False)
    scale = op.attr("scale", None)
    if axis in ctx.mesh_axes:
        out = ulysses_attention(
            q, k, v, axis, ctx.axis_sizes[axis], causal=causal, scale=scale
        )
    else:
        out = _attention_fallback(q, k, v, causal, scale)
    return {"Out": [out]}
