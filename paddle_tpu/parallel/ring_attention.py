"""Sequence/context parallelism: ring attention and Ulysses.

The reference has NO long-context parallelism (SURVEY.md §5: "absent") —
this is greenfield, designed TPU-first:

* Ring attention: the sequence axis is sharded over the "sp" mesh axis;
  each device keeps its Q shard resident and K/V shards rotate around the
  ring via lax.ppermute (ICI neighbor exchange). The ring is a lax.scan
  over ring steps — ONE ppermute pair in the compiled program regardless
  of mesh size, so HLO size and compile time are flat from n=8 to a
  n=256 pod slice (an unrolled Python loop grows both linearly). Softmax
  is merged across shards in logsumexp form (the associative online-
  softmax merge), so no S×S matrix ever materializes.

* Backward is a hand-written ring pass (jax.custom_vjp), the standard
  flash split given the saved global row-logsumexp: dq accumulates on the
  resident q shard; dk/dv accumulators TRAVEL with the visiting K/V shard
  and arrive home after the full rotation. Residual memory is O(S_local)
  per device — the generic scan transpose would stack every visiting
  shard (O(S) per device), exactly the memory SP exists to shed.

* The per-shard block runs in VMEM via the Pallas kernels in
  kernels/ring_block.py whenever the shapes tile (128%head_dim==0, packed
  heads a multiple of 128 lanes; any shape in interpret mode), with a
  chunked jnp online-softmax fallback otherwise (_KV_CHUNK-sized key
  blocks — still O(S_local × chunk) memory).

* Causal rings skip DEAD shards entirely (lax.cond on "is this visiting
  shard wholly above the diagonal"): half the ring steps do no attention
  math, mirroring the tiled kernel's dead-tile skip at tile granularity.

* Ulysses: all_to_all reshard from sequence-sharded to head-sharded,
  full local attention (same Pallas block, offsets 0), all_to_all back.
  One pair of all_to_alls per layer vs n_ring ppermutes; better when
  heads ≥ mesh axis size.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..kernels.ring_block import (
    ring_supports,
    shard_dkv,
    shard_dq,
    shard_fwd,
)

_NEG = -1e30  # finite stand-in for -inf: keeps exp() NaN-free on fully
# masked blocks (a ring step where every key is causally ahead of this query
# shard) — p is zeroed through `valid` instead of relying on exp(-inf)

# test hook: force the chunked-jnp shard backend even where the Pallas
# kernels support the shapes (tests/test_longcontext.py exercises both)
_FORCE_JNP = False


def _online_block(q, k, v, valid, m, l, acc, scale):
    """One blockwise-attention accumulation step (online softmax).

    q [B,H,Sq,D], k/v [B,H,Sk,D], valid broadcastable bool [Sq,Sk] or None,
    m/l running max/denominator [B,H,Sq,1], acc [B,H,Sq,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


# keys processed per online-softmax block: bounds the materialized score
# block to [S_local, _KV_CHUNK] regardless of shard size, so the jnp
# fallback scales to shards far beyond the [S_local, S_local] HBM cliff
_KV_CHUNK = 1024


def _valid_mask(row0, col0, sq, sk):
    rows = row0 + jnp.arange(sq)[:, None]
    cols = col0 + jnp.arange(sk)[None, :]
    return rows >= cols


def _online_shard(qf, kf, vf, row0, col0, causal, m, l, acc, scale):
    """Accumulate one full K/V shard into the running softmax state,
    scanning _KV_CHUNK-sized key blocks (lax.scan) when the shard is
    larger — the in-XLA analog of the Pallas KV tiling."""
    sq = qf.shape[2]
    sk = kf.shape[2]
    if sk <= _KV_CHUNK:
        valid = _valid_mask(row0, col0, sq, sk) if causal else None
        return _online_block(qf, kf, vf, valid, m, l, acc, scale)

    # jax.checkpoint: WITHOUT it the scan's backward saves each chunk's
    # softmax residuals — re-materializing the very [Sq, sk]-sized memory
    # the chunking exists to avoid. (The ring path no longer differentiates
    # through this — custom_vjp below — but Ulysses' jnp fallback still
    # does.)
    @jax.checkpoint
    def body(carry, i):
        m_, l_, acc_ = carry
        kc = lax.dynamic_slice_in_dim(kf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        vc = lax.dynamic_slice_in_dim(vf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        valid = (
            _valid_mask(row0, col0 + i * _KV_CHUNK, sq, _KV_CHUNK)
            if causal else None
        )
        return _online_block(qf, kc, vc, valid, m_, l_, acc_, scale), None

    chunks = sk // _KV_CHUNK
    (m, l, acc), _ = lax.scan(body, (m, l, acc), jnp.arange(chunks))
    tail = sk - chunks * _KV_CHUNK
    if tail:
        # non-multiple shard: the remainder is ONE small block — never the
        # full [sq, sk] score block
        kc = kf[:, :, chunks * _KV_CHUNK:]
        vc = vf[:, :, chunks * _KV_CHUNK:]
        valid = (
            _valid_mask(row0, col0 + chunks * _KV_CHUNK, sq, tail)
            if causal else None
        )
        m, l, acc = _online_block(qf, kc, vc, valid, m, l, acc, scale)
    return m, l, acc


def _online_init(b, h, sq, d):
    return (
        jnp.full((b, h, sq, 1), _NEG, dtype=jnp.float32),
        jnp.zeros((b, h, sq, 1), dtype=jnp.float32),
        jnp.zeros((b, h, sq, d), dtype=jnp.float32),
    )


def _online_finalize(l, acc):
    return acc / jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0


# ---------------------------------------------------------------------------
# per-shard backends: (o_s, lse_s) forward / (dq, dk, dv) backward
# ---------------------------------------------------------------------------


def _pack(x):  # [B,H,S,D] -> [B,S,H*D] (flash lane layout)
    b, h, s, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)


def _unpack(x, h):  # [B,S,H*D] -> [B,H,S,D]
    b, s, hd = x.shape
    return x.reshape(b, s, h, hd // h).transpose(0, 2, 1, 3)


def _shard_fwd_jnp(qf, kf, vf, row0, col0, causal, scale):
    """Self-contained shard attention -> (o_s [B,H,Sq,D] f32,
    lse_s [B,H,Sq,1] f32); fully-masked rows give o=0, lse=_NEG."""
    b, h, sq, d = qf.shape
    m, l, acc = _online_init(b, h, sq, d)
    m, l, acc = _online_shard(qf, kf, vf, row0, col0, causal, m, l, acc,
                              scale)
    o_s = _online_finalize(l, acc)
    lse_s = jnp.where(l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    return o_s, lse_s


def _bwd_block_jnp(qf, kc, vc, do, lse, delta, valid, scale):
    """Flash backward for one [Sq, chunk] block given GLOBAL lse/delta."""
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc) * scale
    p = jnp.exp(s - lse)
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, do)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do, vc)
    ds = p * (dp - delta)
    dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kc) * scale
    dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq_c, dk_c, dv_c


def _shard_bwd_jnp(qf, kf, vf, do, lse, delta, row0, col0, causal, scale):
    """(dq, dk, dv) f32 for one visiting shard, _KV_CHUNK-blocked."""
    sq, sk = qf.shape[2], kf.shape[2]
    if sk <= _KV_CHUNK:
        valid = _valid_mask(row0, col0, sq, sk) if causal else None
        return _bwd_block_jnp(qf, kf, vf, do, lse, delta, valid, scale)

    chunks = sk // _KV_CHUNK

    def body(dq_acc, i):
        kc = lax.dynamic_slice_in_dim(kf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        vc = lax.dynamic_slice_in_dim(vf, i * _KV_CHUNK, _KV_CHUNK, axis=2)
        valid = (
            _valid_mask(row0, col0 + i * _KV_CHUNK, sq, _KV_CHUNK)
            if causal else None
        )
        dq_c, dk_c, dv_c = _bwd_block_jnp(qf, kc, vc, do, lse, delta,
                                          valid, scale)
        return dq_acc + dq_c, (dk_c, dv_c)

    dq, (dks, dvs) = lax.scan(body, jnp.zeros_like(qf), jnp.arange(chunks))

    def _restitch(parts):  # [chunks,B,H,CH,D] -> [B,H,chunks*CH,D]
        c, b, h, ch, d = parts.shape
        return parts.transpose(1, 2, 0, 3, 4).reshape(b, h, c * ch, d)

    dk, dv = _restitch(dks), _restitch(dvs)
    tail = sk - chunks * _KV_CHUNK
    if tail:
        kc = kf[:, :, chunks * _KV_CHUNK:]
        vc = vf[:, :, chunks * _KV_CHUNK:]
        valid = (
            _valid_mask(row0, col0 + chunks * _KV_CHUNK, sq, tail)
            if causal else None
        )
        dq_t, dk_t, dv_t = _bwd_block_jnp(qf, kc, vc, do, lse, delta,
                                          valid, scale)
        dq = dq + dq_t
        dk = jnp.concatenate([dk, dk_t], axis=2)
        dv = jnp.concatenate([dv, dv_t], axis=2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# the ring: scan-rolled forward + custom_vjp ring backward
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_core(q, k, v, axis_name, n, causal, scale, backend, interpret):
    out, _lse = _ring_fwd_impl(q, k, v, axis_name, n, causal, scale,
                               backend, interpret)
    return out


def _ring_perm(n):
    return [(i, (i - 1) % n) for i in range(n)]  # send to left neighbor


def _ring_fwd_impl(q, k, v, axis_name, n, causal, scale, backend, interpret):
    """Step 0 (the resident shard — always live under causal) is hoisted
    out of the scan so the ring does exactly n-1 rotations: a scan body of
    rotate-then-compute never pays a final dead transfer, and the HLO still
    contains ONE ppermute pair regardless of n."""
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = _ring_perm(n)

    if backend == "pallas":
        qp = _pack(q)
        kt0, vt0 = _pack(k), _pack(v)

        def sfwd(kt, vt, src):
            offs = jnp.stack([idx * s_local, src * s_local]).astype(jnp.int32)
            return shard_fwd(qp, kt, vt, offs, h, d, causal, scale,
                             interpret)

        dead = lambda _: (jnp.zeros(qp.shape, jnp.float32),
                          jnp.full(qp.shape, _NEG, jnp.float32))
        finish = lambda o: _unpack(o, h).astype(q.dtype)
    else:
        qf = q.astype(jnp.float32)
        kt0, vt0 = k, v

        def sfwd(kt, vt, src):
            return _shard_fwd_jnp(
                qf, kt.astype(jnp.float32), vt.astype(jnp.float32),
                idx * s_local, src * s_local, causal, scale,
            )

        dead = lambda _: (jnp.zeros(q.shape, jnp.float32),
                          jnp.full((b, h, s_local, 1), _NEG, jnp.float32))
        finish = lambda o: o.astype(q.dtype)

    o, l = sfwd(kt0, vt0, idx)  # step 0: diagonal shard

    def body(carry, t):
        kt, vt, o, l = carry
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        src = (idx + t) % n
        if causal:
            # a shard wholly above the diagonal contributes nothing: skip
            # its kernel at runtime (half the ring on average)
            os_, ls_ = lax.cond(src <= idx, lambda _: sfwd(kt, vt, src),
                                dead, None)
        else:
            os_, ls_ = sfwd(kt, vt, src)
        l_new = jnp.logaddexp(l, ls_)
        o = o * jnp.exp(l - l_new) + os_ * jnp.exp(ls_ - l_new)
        return (kt, vt, o, l_new), None

    if n > 1:
        (_, _, o, l), _ = lax.scan(body, (kt0, vt0, o, l), jnp.arange(1, n))
    # lse layout: packed [B,Sq,H*D] (pallas) / [B,H,Sq,1] (jnp)
    return finish(o), l


def _slim_lse(lse, h, d, backend):
    """Residual diet: the packed lse is column-replicated D times — keep
    one column per head across the fwd->bwd interval ([B,Sq,H] instead of
    [B,Sq,H*D]; at long context that residual is activation-sized)."""
    if backend == "pallas":
        b, s, hd = lse.shape
        return lse.reshape(b, s, h, d)[..., 0]
    return lse  # jnp layout is already [B,H,Sq,1]


def _fatten_lse(lse, d, backend):
    if backend == "pallas":
        return jnp.repeat(lse, d, axis=-1)
    return lse


def _pallas_bwd_prep(q, k, v, out, g, lse_slim, causal, scale, interpret):
    """Shared pallas backward plumbing (ring and Ulysses): pack operands,
    rebuild the lane-replicated lse, compute fp32 delta=rowsum(do*out),
    and return a per-shard (kt, vt, offs) -> (dq, dk, dv) closure."""
    b, h, s, d = q.shape
    lse = _fatten_lse(lse_slim, d, "pallas")
    qp, gp, op = _pack(q), _pack(g), _pack(out)
    delta = jnp.sum(
        gp.astype(jnp.float32).reshape(b, s, h, d)
        * op.astype(jnp.float32).reshape(b, s, h, d),
        axis=-1,
    )  # [B,Sq,H]
    delta = jnp.repeat(delta, d, axis=-1)  # column-replicated [B,Sq,H*D]

    def sbwd(kt, vt, offs):
        dq_c = shard_dq(qp, kt, vt, gp, lse, delta, offs, h, d, causal,
                        scale, interpret)
        dk_c, dv_c = shard_dkv(qp, kt, vt, gp, lse, delta, offs, h, d,
                               causal, scale, interpret)
        return dq_c, dk_c, dv_c

    return qp, sbwd


def _ring_core_fwd(q, k, v, axis_name, n, causal, scale, backend, interpret):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, n, causal, scale,
                              backend, interpret)
    h, d = q.shape[1], q.shape[3]
    return out, (q, k, v, out, _slim_lse(lse, h, d, backend))


def _ring_core_bwd(axis_name, n, causal, scale, backend, interpret, res, g):
    """Ring backward: dq accumulates on the resident q shard; dk/dv
    accumulators TRAVEL with the visiting k/v shard. Step 0 is hoisted
    (n-1 in-scan rotations), so one final hop outside the scan brings each
    accumulator home fully summed: n transfers total — the minimum for a
    backward that must return remote-shard gradients."""
    q, k, v, out, lse_slim = res
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = _ring_perm(n)

    if backend == "pallas":
        qp, shard_bwd = _pallas_bwd_prep(q, k, v, out, g, lse_slim,
                                         causal, scale, interpret)
        kt0, vt0 = _pack(k), _pack(v)
        zeros_q = jnp.zeros(qp.shape, jnp.float32)
        zeros_kv = jnp.zeros(kt0.shape, jnp.float32)

        def sbwd(kt, vt, src):
            offs = jnp.stack([idx * s_local, src * s_local]).astype(jnp.int32)
            return shard_bwd(kt, vt, offs)

        def finish(x, like):
            return _unpack(x, h).astype(like.dtype)
    else:
        lse = lse_slim  # jnp layout needs no fattening
        qf = q.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        of = out.astype(jnp.float32)
        kt0, vt0 = k, v
        delta = jnp.sum(gf * of, axis=-1, keepdims=True)  # [B,H,Sq,1]
        zeros_q = jnp.zeros(q.shape, jnp.float32)
        zeros_kv = jnp.zeros(k.shape, jnp.float32)

        def sbwd(kt, vt, src):
            return _shard_bwd_jnp(
                qf, kt.astype(jnp.float32), vt.astype(jnp.float32), gf,
                lse, delta, idx * s_local, src * s_local, causal, scale,
            )

        def finish(x, like):
            return x.astype(like.dtype)

    dq, dk0, dv0 = sbwd(kt0, vt0, idx)  # step 0: diagonal shard

    def body(carry, t):
        kt, vt, dkt, dvt, dq = carry
        kt, dkt = (lax.ppermute(x, axis_name, perm) for x in (kt, dkt))
        vt, dvt = (lax.ppermute(x, axis_name, perm) for x in (vt, dvt))
        src = (idx + t) % n
        if causal:
            dq_c, dk_c, dv_c = lax.cond(
                src <= idx, lambda _: sbwd(kt, vt, src),
                lambda _: (zeros_q, zeros_kv, zeros_kv), None,
            )
        else:
            dq_c, dk_c, dv_c = sbwd(kt, vt, src)
        return (kt, vt, dkt + dk_c, dvt + dv_c, dq + dq_c), None

    if n > 1:
        (_, _, dkt, dvt, dq), _ = lax.scan(
            body, (kt0, vt0, dk0, dv0, dq), jnp.arange(1, n)
        )
        # accumulators sit one hop from home after n-1 rotations
        dkt = lax.ppermute(dkt, axis_name, perm)
        dvt = lax.ppermute(dvt, axis_name, perm)
    else:
        dkt, dvt = dk0, dv0
    return finish(dq, q), finish(dkt, k), finish(dvt, v)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q, k, v, axis_name, axis_size, causal=False, scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D] inside shard_map.

    Returns [B, H, S_local, D]. Rotates K/V around the ring; at step t this
    device (index i) processes the K/V shard originating at (i + t) mod n.
    """
    n = int(axis_size)
    b, h, s_local, d = q.shape
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    # interpret-mode Pallas is a CPU-test affordance; on other non-TPU
    # backends (gpu) the chunked-jnp path is the compiled fallback
    interpret = jax.default_backend() == "cpu"
    use_pallas = (
        not _FORCE_JNP
        and (interpret or jax.default_backend() == "tpu")
        and ring_supports(s_local, s_local, h, d, q.dtype, interpret)
    )
    backend = "pallas" if use_pallas else "jnp"
    return _ring_core(q, k, v, axis_name, n, causal, scale, backend,
                      interpret)


# ---------------------------------------------------------------------------
# Ulysses: all_to_all head resharding + full local attention
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _local_flash(q, k, v, causal, scale, interpret):
    """Full (unsharded-sequence) attention through the ring-block Pallas
    kernels, offsets 0 — the Ulysses local step and any future dense use."""
    out, _lse = _local_flash_fwd(q, k, v, causal, scale, interpret)
    return out


def _local_flash_fwd(q, k, v, causal, scale, interpret):
    h, d = q.shape[1], q.shape[3]
    offs = jnp.zeros(2, jnp.int32)
    o, lse = shard_fwd(_pack(q), _pack(k), _pack(v), offs, h, d, causal,
                       scale, interpret)
    return _unpack(o, h).astype(q.dtype), lse


def _local_flash_vjp_fwd(q, k, v, causal, scale, interpret):
    out, lse = _local_flash_fwd(q, k, v, causal, scale, interpret)
    h, d = q.shape[1], q.shape[3]
    return out, (q, k, v, out, _slim_lse(lse, h, d, "pallas"))


def _local_flash_vjp_bwd(causal, scale, interpret, res, g):
    q, k, v, out, lse_slim = res
    h = q.shape[1]
    _, shard_bwd = _pallas_bwd_prep(q, k, v, out, g, lse_slim, causal,
                                    scale, interpret)
    dq, dk, dv = shard_bwd(_pack(k), _pack(v), jnp.zeros(2, jnp.int32))
    return (
        _unpack(dq, h).astype(q.dtype),
        _unpack(dk, h).astype(k.dtype),
        _unpack(dv, h).astype(v.dtype),
    )


_local_flash.defvjp(_local_flash_vjp_fwd, _local_flash_vjp_bwd)


def ulysses_attention(q, k, v, axis_name, axis_size, causal=False, scale=None):
    """q,k,v: LOCAL shards [B, H, S_local, D]; H must divide axis_size.

    all_to_all: seq-sharded -> head-sharded, dense local attention over the
    FULL sequence, all_to_all back (head-sharded -> seq-sharded).
    """
    n = int(axis_size)
    b, h, s_local, d = q.shape
    if h % n:
        raise ValueError(f"ulysses: heads {h} not divisible by axis size {n}")

    def to_heads(x):  # [B,H,Sl,D] -> [B,H/n,S,D]
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def to_seq(x):  # [B,H/n,S,D] -> [B,H,Sl,D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    bh, hh, s_full, _ = qh.shape
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    interpret = jax.default_backend() == "cpu"
    if (not _FORCE_JNP
            and (interpret or jax.default_backend() == "tpu")
            and ring_supports(s_full, s_full, hh, d, qh.dtype, interpret)):
        return to_seq(_local_flash(qh, kh, vh, causal, scale, interpret))
    # jnp fallback: stream the full sequence through the same chunked
    # online softmax as the ring path — a dense [S, S] block at long
    # context is exactly the cliff SP exists to avoid
    m, l, acc = _online_init(bh, hh, s_full, d)
    m, l, acc = _online_shard(
        qh.astype(jnp.float32), kh.astype(jnp.float32),
        vh.astype(jnp.float32), 0, 0, causal, m, l, acc, scale,
    )
    return to_seq(_online_finalize(l, acc).astype(q.dtype))


# ---------------------------------------------------------------------------
# op registrations (static graph)
# ---------------------------------------------------------------------------

from ..framework.registry import register_op  # noqa: E402


def _attention_fallback(q, k, v, causal, scale):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        n = s.shape[-1]
        mask = jnp.arange(n)[:, None] >= jnp.arange(n)[None, :]
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register_op("ring_attention", inputs=["Q", "K", "V"], outputs=["Out"])
def _ring_attention_op(ctx, op, ins):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    axis = op.attr("axis_name", "sp")
    causal = op.attr("causal", False)
    scale = op.attr("scale", None)
    if axis in ctx.mesh_axes:
        out = ring_attention(
            q, k, v, axis, ctx.axis_sizes[axis], causal=causal, scale=scale
        )
    else:  # single-shard: dense attention (nranks==1 degradation)
        out = _attention_fallback(q, k, v, causal, scale)
    return {"Out": [out]}


@register_op("ulysses_attention", inputs=["Q", "K", "V"], outputs=["Out"])
def _ulysses_attention_op(ctx, op, ins):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    axis = op.attr("axis_name", "sp")
    causal = op.attr("causal", False)
    scale = op.attr("scale", None)
    if axis in ctx.mesh_axes:
        out = ulysses_attention(
            q, k, v, axis, ctx.axis_sizes[axis], causal=causal, scale=scale
        )
    else:
        out = _attention_fallback(q, k, v, causal, scale)
    return {"Out": [out]}
