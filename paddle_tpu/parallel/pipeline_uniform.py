"""Stage-uniform pipeline parallelism (GSPMD-pipelining style).

The general `pipeline_block` (pipeline.py) dispatches per-device stages with
lax.switch — faithful to the reference's heterogeneous SectionWorker
sections (section_worker.cc:142), but SPMD-illegal to compose with
gspmd-Auto tensor parallelism: the partitioner places mp collectives INSIDE
the switch branches, devices take different branches by pp rank, and a
subset of a global collective's participants deadlocks (reproduced on the
8-device virtual mesh; the same program would hang a real pod).

This module is the TPU-native composition answer, the design XLA's own
pipelining work uses: make the pipeline body STAGE-UNIFORM so there is no
branch at all.

  * The user builds ONE stage's ops (a template sub-block). Its parameters
    become [K, ...]-STACKED real parameters sharded over the pp axis —
    under manual-pp shard_map each device's local shard IS its own stage's
    weights. Weight selection is sharding, not control flow.
  * Every device runs the identical stage computation per tick; mp
    collectives (auto-axis, partitioner-inserted) therefore execute
    uniformly on all devices — composition with tensor parallelism is
    safe by construction.
  * The GPipe schedule is the same lax.scan + lax.ppermute ring as
    pipeline.py; stage inputs are injected at rank 0, final-stage outputs
    accumulate into a [M, b, ...] buffer on rank K-1 and are replicated by
    one psum so the (unpipelined) head runs on every device.
  * Parameters AND optimizer state shard by stage: params/opt bytes per
    device divide by K — the memory scaling the lax.switch design cannot
    give (it replicates every stage's weights everywhere).
  * Backward needs NO per-grad pp allreduce for stacked params: each
    device's grad slice is exactly its stage's gradient. Only params
    outside the pipeline (embeddings, head) need one — and `gate_loss`
    arranges that every outside grad is a single-rank contribution, so a
    plain psum is correct for all of them.

Reference provenance: capability = PipelineOptimizer optimizer.py:3556 +
SectionWorker section_worker.cc:142 (schedule), composed with
RecomputeOptimizer optimizer.py:3858 (remat attr) and the AMP rewrite; the
stacked-weight formulation itself is TPU-native (no reference analogue —
NCCL pipelines never needed it because each rank ran a different program).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import unique_name
from ..framework.registry import register_op, run_op


def _uniform_infer(block, inputs, attrs):
    x = block.var(inputs["X"][0])
    return {"Out": [(tuple(x.shape), x.dtype)]}


@register_op(
    "pipeline_uniform",
    inputs=["X", "MbExtern", "Stacked"],
    outputs=["Out"],
    infer_shape=_uniform_infer,
)
def _pipeline_uniform(ctx, op, ins):
    prog = ctx.program
    blk = prog.blocks[op.attr("stage_block")]
    K = op.attr("num_stages")
    M = op.attr("num_microbatches")
    axis = op.attr("axis_name", "pp")
    in_name = op.attr("in_name")
    out_name = op.attr("out_name")
    mb_names = op.attr("mb_extern_names")
    tmpl_names = op.attr("template_names")
    remat = op.attr("remat", False)
    b_dtype = np.dtype(op.attr("boundary_dtype"))

    x = ins["X"][0]
    mb_extern = dict(zip(mb_names, ins.get("MbExtern", [])))
    stacked = ins.get("Stacked", [])

    if x.shape[0] % M:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches={M}"
        )
    bm = x.shape[0] // M
    x_mb = x.reshape((M, bm) + x.shape[1:]).astype(b_dtype)
    mb_views = {
        nm: v.reshape((M, bm) + v.shape[1:]) for nm, v in mb_extern.items()
    }
    base_key = (
        ctx.step_key if ctx.step_key is not None else jax.random.key(0)
    )

    def stage_fn(act_in, mb_idx, tick_key, params):
        env = dict(zip(tmpl_names, params))
        idx = jnp.clip(mb_idx, 0, M - 1)
        for nm, v in mb_views.items():
            env[nm] = lax.dynamic_index_in_dim(v, idx, 0, keepdims=False)
        env[in_name] = act_in
        sub_ctx = ctx.with_key(tick_key).with_batch_divisor(M)
        for sub_op in blk.ops:
            run_op(sub_ctx, sub_op, env)
        return env[out_name].astype(b_dtype)

    if remat:
        # reference RecomputeOptimizer composition: the stage is one
        # rematerialized segment — backward re-runs it from the boundary
        stage_fn = jax.checkpoint(stage_fn)

    if axis not in ctx.mesh_axes:
        # single-device degrade: the K stages run sequentially per
        # microbatch with the full [K, ...] stacks — identical numerics
        # (same fold_in(base, m+k), k key schedule as tick t = m+k on
        # stage k), no pipeline. Both loops are lax.scans so the stage
        # traces ONCE, not M*K times (compile time flat in M and K).
        def stage_step(carry, xs):
            act, m = carry
            k, params = xs
            key = jax.random.fold_in(
                jax.random.fold_in(base_key, m + k), k
            )
            return (stage_fn(act, m, key, list(params)), m), None

        def mb_step(_, m):
            act0 = lax.dynamic_index_in_dim(x_mb, m, 0, keepdims=False)
            (act, _), _ = lax.scan(
                stage_step, (act0, m),
                (jnp.arange(K, dtype=jnp.int32), tuple(stacked)),
            )
            return None, act

        _, outs = lax.scan(mb_step, None, jnp.arange(M, dtype=jnp.int32))
        out = outs.reshape(x.shape).astype(b_dtype)
        return {"Out": [out]}

    K_mesh = ctx.axis_sizes[axis]
    if K_mesh != K:
        raise ValueError(
            f"uniform pipeline has {K} stages but mesh axis {axis!r} has "
            f"size {K_mesh}"
        )
    for s in stacked:
        if s.shape[0] != 1:
            raise ValueError(
                "stacked param arrived unsharded inside the mesh body "
                f"(leading dim {s.shape[0]}, expected 1): annotate it "
                f"('{axis}', ...) and run in hybrid/shard_map mode"
            )
    local_params = [s[0] for s in stacked]  # this device's stage weights
    stage_id = lax.axis_index(axis)
    fwd_perm = [(i, (i + 1) % K) for i in range(K)]

    def tick(carry, t):
        send, outbuf = carry
        recv = lax.ppermute(send, axis, fwd_perm)
        mb_idx = t - stage_id
        idx = jnp.clip(mb_idx, 0, M - 1)
        first = lax.dynamic_index_in_dim(x_mb, idx, 0, keepdims=False)
        act_in = jnp.where(stage_id == 0, first, recv)
        # fold the stage id in too: uniform stages share op uids, so a
        # tick-only key would draw the IDENTICAL dropout mask on every
        # stage (the degrade path mirrors this as fold(base, m+k), k)
        key = jax.random.fold_in(
            jax.random.fold_in(base_key, t), stage_id
        )
        out = stage_fn(act_in, mb_idx, key, local_params)
        valid = jnp.logical_and(mb_idx >= 0, mb_idx < M)
        collect = jnp.logical_and(valid, stage_id == K - 1)
        upd = lax.dynamic_update_index_in_dim(
            outbuf, out.astype(outbuf.dtype), idx, 0
        )
        outbuf = jnp.where(collect, upd, outbuf)
        return (out, outbuf), None

    init = (
        jnp.zeros((bm,) + x.shape[1:], b_dtype),
        jnp.zeros((M, bm) + x.shape[1:], b_dtype),
    )
    (_, outbuf), _ = lax.scan(
        tick, init, jnp.arange(M + K - 1, dtype=jnp.int32)
    )
    # outbuf is populated only on rank K-1; replicate it so the
    # (unpipelined) head runs everywhere. Transpose of psum is psum under
    # shard_map, but the incoming cotangent is nonzero on rank K-1 only
    # (gate_loss), so the backward psum broadcasts — not scales — it.
    out = lax.psum(outbuf, axis).reshape(x.shape).astype(b_dtype)
    return {"Out": [out]}


def _gate_infer(block, inputs, attrs):
    v = block.var(inputs["X"][0])
    return {"Out": [(tuple(v.shape), v.dtype)]}


@register_op("pipeline_gate_loss", inputs=["X"], outputs=["Out"],
             infer_shape=_gate_infer)
def _pipeline_gate_loss(ctx, op, ins):
    """Replicated loss whose COTANGENT originates on the last pipeline rank
    only. Value: x (every rank computed the identical head loss from the
    psum-replicated pipeline output). Backward: the where() kills every
    rank's seed except rank K-1's, so all outside-the-pipeline gradients
    (embeddings upstream, head downstream) become single-rank contributions
    — one psum over pp per grad then yields the true gradient everywhere
    (appended by the builder, see uniform_pipeline docstring)."""
    x = ins["X"][0]
    axis = op.attr("axis_name", "pp")
    if axis not in ctx.mesh_axes:
        return {"Out": [x]}
    K = ctx.axis_sizes[axis]
    r = lax.axis_index(axis)
    gated = jnp.where(r == K - 1, x, jnp.zeros_like(x))
    total = lax.psum(gated, axis)
    # psum transposes to psum under shard_map: each rank's unit seed would
    # arrive K-fold at the gate. Scale the COTANGENT by 1/K, not the value
    # (same correction as pipeline.py:196).
    return {"Out": [total / K + lax.stop_gradient(total * (K - 1) / K)]}


def uniform_pipeline(x, stage_builder, num_stages, num_microbatches,
                     mb_extern=(), axis_name="pp", remat=False,
                     name="upipe"):
    """Build a stage-uniform pipeline over `x` ([B, ...] activations).

    stage_builder(x_var) -> out_var is called ONCE inside a fresh
    sub-block; every parameter it creates becomes a TEMPLATE whose real,
    trained parameter is a [num_stages, ...] stack sharded over
    `axis_name`. out_var must have x's shape/dtype (uniformity).

    mb_extern: batch-leading Variables every stage reads (e.g. the
    attention mask) — sliced per microbatch like x.

    Returns the [B, ...] final-stage output (replicated). The builder also
    records the stack (and its Adam-moment) shardings on the program.

    After `optimizer.minimize`, call `append_outside_grad_allreduce` so
    non-stacked parameter grads are psum'd over pp — and wrap the loss in
    `gate_loss` FIRST so those grads are single-rank contributions.
    """
    from ..framework.program import default_main_program, default_startup_program

    main = default_main_program()
    startup = default_startup_program()
    gb = main.global_block

    before = {p.name for p in gb.all_parameters()}
    sub = main.create_block()
    try:
        x_in = sub.create_var(
            name=unique_name.generate(f"{name}_in"), shape=x.shape,
            dtype=x.dtype,
        )
        out_var = stage_builder(x_in)
    finally:
        main.rollback()
    if tuple(out_var.shape) != tuple(x.shape):
        raise ValueError(
            f"uniform pipeline stage must preserve shape: in {x.shape}, "
            f"out {out_var.shape}"
        )
    tmpl = [p for p in gb.all_parameters() if p.name not in before]

    # real trained params: [K, ...] stacks; startup init is the template's
    # init op re-shaped (independent init per stage slice)
    K = int(num_stages)
    stacked_names = []
    sb = startup.global_block
    for p in tmpl:
        sname = f"{p.name}@STACK"
        stacked_names.append(sname)
        gb.create_parameter(
            sname, (K,) + tuple(p.shape), p.dtype, trainable=True
        )
        init_ops = [o for o in sb.ops if p.name in o.output_names()]
        if len(init_ops) != 1:
            raise ValueError(
                f"template param {p.name!r} has {len(init_ops)} startup "
                "init ops; expected exactly 1"
            )
        io = init_ops[0]
        attrs = dict(io.attrs)
        if "shape" in attrs:
            attrs["shape"] = [K] + list(attrs["shape"])
        sb.create_parameter(sname, (K,) + tuple(p.shape), p.dtype)
        sb.append_op(io.type, {k: list(v) for k, v in io.inputs.items()},
                     {k: [sname] for k in io.outputs}, attrs)
        # the template itself is never trained or materialized: drop its
        # startup init and demote it to a plain shape/dtype declaration
        sb.ops.remove(io)
        sb.vars.pop(p.name, None)
        p.trainable = False
        p.persistable = False
        # stacks shard over the pp axis — each device holds exactly its
        # stage's slice (optimizer accumulators inherit this spec via
        # spec_for's _accum_of fallback, whatever unique suffix they get)
        main._sharding[sname] = (axis_name,)

    out = gb.create_var(
        name=unique_name.generate(f"{name}_out"), shape=x.shape,
        dtype=x.dtype,
    )
    gb.append_op(
        "pipeline_uniform",
        {
            "X": [x.name],
            "MbExtern": [v.name for v in mb_extern],
            "Stacked": list(stacked_names),
        },
        {"Out": [out.name]},
        {
            "stage_block": sub.idx,
            "num_stages": K,
            "num_microbatches": int(num_microbatches),
            "axis_name": axis_name,
            "in_name": x_in.name,
            "out_name": out_var.name,
            "mb_extern_names": [v.name for v in mb_extern],
            "template_names": [p.name for p in tmpl],
            "remat": bool(remat),
            "boundary_dtype": str(x.dtype),
        },
    )
    return out


def gate_loss(loss, axis_name="pp"):
    """Wrap the scalar loss so its cotangent originates on the last pp rank
    only (see pipeline_gate_loss). Call before optimizer.minimize."""
    blk = loss.block
    out = blk.create_var(
        name=unique_name.generate(f"{loss.name}@GATED"),
        shape=tuple(loss.shape or (1,)), dtype=loss.dtype,
    )
    blk.append_op(
        "pipeline_gate_loss", {"X": [loss.name]}, {"Out": [out.name]},
        {"axis_name": axis_name},
    )
    return out


def append_outside_grad_allreduce(program, params_grads, axis_name="pp"):
    """psum non-stacked param grads over pp: with gate_loss in place each is
    a single-rank contribution (embeddings live on rank 0's cotangent path,
    head grads on rank K-1's), so a plain sum is the true gradient. Stacked
    params need nothing — each device's slice IS its stage's grad. Inserted
    before AMP bookkeeping ops (same rule as parallel/transpiler.py)."""
    from .transpiler import insert_grad_allreduce

    block = program.global_block
    stacked = {
        n
        for op in block.ops
        if op.type == "pipeline_uniform"
        for n in op.inputs.get("Stacked", [])
    }
    for p, g in params_grads:
        pname = p.name if hasattr(p, "name") else str(p)
        if pname in stacked:
            continue
        insert_grad_allreduce(block, g, axis_name)
    return program
