"""Sharded-table bookkeeping for the sparse/PS path.

Reference: the DistributeTranspiler sliced each table into per-pserver
blocks and rewired the trainer program with prefetch/send ops
(transpiler/distribute_transpiler.py:1675, ps_dispatcher.py). Here the
"transpile" is pure metadata: mark every sparse table (and its grad +
optimizer accumulators) as row-sharded over the mesh axis, then let
shard_map place the shards. See ops/sparse.py for the lookup kernel.
"""

from __future__ import annotations

from ..framework.program import grad_var_name


def sparse_table_names(program):
    """Names of every table consumed by a distributed_lookup_table op."""
    names = []
    for blk in program.blocks:
        for op in blk.ops:
            if op.type == "distributed_lookup_table":
                w = op.inputs["W"][0]
                if w not in names:
                    names.append(w)
    return names


def shard_sparse_tables(program, axis="ps"):
    """Row-shard every sparse table + grad + optimizer state over `axis`.

    Call AFTER optimizer.minimize (so accumulator vars exist) and before
    shard_program. Optimizer accumulators are matched by the exact
    `_accum_of` tag Optimizer._add_accumulator stamps on each accumulator
    Variable (row-shaped ones only; scalar state like beta powers stays
    replicated) — row-sharding them keeps Adam/SGD state local to the
    owning shard, the locality the reference's per-pserver optimize blocks
    (listen_and_serv_op.cc) achieved over RPC. Custom state created outside
    _add_accumulator is NOT auto-sharded; tag it with `_accum_of` yourself.
    """
    tables = sparse_table_names(program)
    blk = program.global_block
    for t in tables:
        rows = blk.var(t).shape[0]
        program._sharding[t] = (axis,)
        # divisibility is NOT auto-padded at this layer: fail loudly at
        # build time instead of an opaque shard_map error at run time
        # (sparse_embedding's pad_to_multiple should cover the mesh size)
        if program._mesh is not None and axis in program._mesh.shape:
            n = program._mesh.shape[axis]
            if rows % n:
                raise ValueError(
                    f"table {t!r} has {rows} rows, not divisible by mesh "
                    f"axis {axis!r} size {n}; raise pad_to_multiple on "
                    "sparse_embedding"
                )
        program._sharding[grad_var_name(t)] = (axis,)
        for name, v in blk.vars.items():
            # exact match on the optimizer's accumulator tag (row-shaped
            # only; scalar state like beta powers stays replicated)
            if (
                getattr(v, "_accum_of", None) == t
                and v.shape
                and len(v.shape) >= 1
                and v.shape[0] == rows
            ):
                program._sharding[name] = (axis,)
    for blk_ in program.blocks:
        for op in blk_.ops:
            if op.type == "distributed_lookup_table":
                # unconditional: a stale axis_name from build time would
                # shard storage over one axis but psum over another
                op.attrs["axis_name"] = axis
    program._bump()
    return tables
