"""Sharded-table bookkeeping for the sparse/PS path.

Reference: the DistributeTranspiler sliced each table into per-pserver
blocks and rewired the trainer program with prefetch/send ops
(transpiler/distribute_transpiler.py:1675, ps_dispatcher.py). Here the
"transpile" is pure metadata: mark every sparse table (and its grad +
optimizer accumulators) as sharded over the mesh axis, then let shard_map
place the shards. See ops/sparse.py for the lookup kernel and
``paddle_tpu.embedding`` for the fused-lookup transform + cache tiers.

Two partitions (PR 11):

* ``partition="row"`` — [V/n, D] shards; a lookup masks to the owned row
  segment and psum-assembles (ids are replicated, so there is no forward
  id exchange; the backward row-gradient exchange optionally rides the
  PR-9 int8 wire, see ``quantize_embedding_grads``).
* ``partition="col"`` — [V, D/n] shards; a lookup gathers every row's
  column slice locally and all-gathers over the feature dim (the Megatron
  embedding split). Quantized grad exchange is row-partition only.
"""

from __future__ import annotations

from ..framework.program import grad_var_name

LOOKUP_OPS = ("distributed_lookup_table", "fused_lookup_table")


def _lookup_tables(op):
    """Table var names consumed by a (possibly fused) lookup op."""
    return [w for w in op.inputs.get("W", ()) if w]


def _stamp_lookup_attrs(program, attrs):
    """Stamp `attrs` onto every lookup op AND onto the ``fwd_attrs``
    snapshot of every ``__vjp__`` grad op replaying one: append_backward
    copies the forward attrs at minimize time, so a post-minimize rewrite
    that only touched the forward op would leave the backward replay
    running the OLD exchange (wrong axis/partition, or silently
    unquantized grads)."""
    stamped = 0
    for blk in program.blocks:
        for op in blk.ops:
            if op.type in LOOKUP_OPS and _lookup_tables(op):
                op.attrs.update(attrs)
                stamped += 1
            elif (
                op.type == "__vjp__"
                and op.attr("fwd_type") in LOOKUP_OPS
            ):
                fwd_attrs = dict(op.attr("fwd_attrs") or {})
                fwd_attrs.update(attrs)
                op.attrs["fwd_attrs"] = fwd_attrs
    program._bump()
    return stamped


def sparse_table_names(program):
    """Names of every table consumed by a lookup op (fused or single)."""
    names = []
    for blk in program.blocks:
        for op in blk.ops:
            if op.type in LOOKUP_OPS:
                for w in _lookup_tables(op):
                    if w not in names:
                        names.append(w)
    return names


def quantize_embedding_grads(program, quant="int8", quant_block=256):
    """Opt in to the int8 block-quantized embedding-gradient exchange on
    every row-partitioned lookup op (the PR-9 EQuARX wire applied to the
    backward row-cotangent psum). ``quant=None``/"none" switches back to
    the fp32 psum, which is bitwise-identical to the pre-engine path."""
    quant = quant if quant not in (None, "", "none") else "none"
    if quant not in ("none", "int8"):
        raise ValueError(
            f"quantize_embedding_grads: unknown quantization {quant!r}; "
            "supported: None | 'int8'"
        )
    if int(quant_block) < 1:
        raise ValueError(
            f"quantize_embedding_grads: quant_block must be a positive "
            f"element count, got {quant_block!r}"
        )
    for blk in program.blocks:
        for op in blk.ops:
            if (
                op.type in LOOKUP_OPS
                and quant != "none"
                and op.attr("partition", "row") == "col"
            ):
                raise NotImplementedError(
                    "quantize_embedding_grads: the column-partitioned "
                    "lookup's grad exchange (psum_scatter over the feature "
                    "dim) is not quantized; use partition='row'"
                )
    return _stamp_lookup_attrs(
        program, {"quant": quant, "quant_block": int(quant_block)}
    )


def shard_sparse_tables(program, axis="ps", partition="row"):
    """Shard every sparse table + grad + optimizer state over `axis`.

    Call AFTER optimizer.minimize (so accumulator vars exist) and before
    shard_program. Optimizer accumulators are matched by the exact
    `_accum_of` tag Optimizer._add_accumulator stamps on each accumulator
    Variable (table-shaped ones only; scalar state like beta powers stays
    replicated) — sharding them keeps Adam/SGD state local to the owning
    shard, the locality the reference's per-pserver optimize blocks
    (listen_and_serv_op.cc) achieved over RPC. Custom state created outside
    _add_accumulator is NOT auto-sharded; tag it with `_accum_of` yourself.

    ``partition``: "row" shards dim 0 ([V/n, D]); "col" shards dim 1
    ([V, D/n], the Megatron embedding split — backward stays a local
    column-slice scatter, no row exchange at all).
    """
    if partition not in ("row", "col"):
        raise ValueError(
            f"shard_sparse_tables: unknown partition {partition!r}; "
            "supported: 'row' | 'col'"
        )
    if partition == "col":
        # order-independent guard: quantize_embedding_grads refuses col
        # AFTER the partition is stamped; stamping col AFTER a quant
        # opt-in would silently drop the compression while telemetry and
        # the collective lint keep claiming int8
        for blk in program.blocks:
            for op in blk.ops:
                if (
                    op.type in LOOKUP_OPS
                    and (op.attr("quant", "none") or "none") != "none"
                ):
                    raise NotImplementedError(
                        "shard_sparse_tables: partition='col' does not "
                        "support the quantized grad exchange stamped on "
                        f"op {op.type!r}; use partition='row' or drop "
                        "quantize_embedding_grads"
                    )
    tables = sparse_table_names(program)
    blk = program.global_block
    dim_idx = 0 if partition == "row" else 1
    spec = (axis,) if partition == "row" else (None, axis)
    for t in tables:
        shape = blk.var(t).shape
        program._sharding[t] = spec
        # divisibility is NOT auto-padded at this layer: fail loudly at
        # build time instead of an opaque shard_map error at run time
        # (sparse_embedding's pad_to_multiple should cover the mesh size
        # for rows; embed_dim must divide the mesh for columns)
        if program._mesh is not None and axis in program._mesh.shape:
            n = program._mesh.shape[axis]
            if shape[dim_idx] % n:
                fix = (
                    "raise pad_to_multiple on sparse_embedding"
                    if dim_idx == 0 else
                    "pick an embed_dim the mesh divides (or "
                    "partition='row')"
                )
                raise ValueError(
                    f"table {t!r} has {shape[dim_idx]} "
                    f"{'rows' if dim_idx == 0 else 'columns'}, not "
                    f"divisible by mesh axis {axis!r} size {n}; {fix}"
                )
        program._sharding[grad_var_name(t)] = spec
        for name, v in blk.vars.items():
            # exact match on the optimizer's accumulator tag (table-shaped
            # only; scalar state like beta powers stays replicated)
            if (
                getattr(v, "_accum_of", None) == t
                and v.shape
                and len(v.shape) > dim_idx
                and tuple(v.shape) == tuple(shape)
            ):
                program._sharding[name] = spec
    # unconditional: a stale axis_name from build time would shard storage
    # over one axis but psum over another; the partition attr must match
    # the storage layout the same way (forward ops AND __vjp__ snapshots)
    _stamp_lookup_attrs(
        program, {"axis_name": axis, "partition": partition}
    )
    return tables
