"""Parallelism: mesh management, SPMD execution, program transpilers.

Replaces the reference's ParallelExecutor + NCCL stack (SURVEY.md §2.1
rows: ParallelExecutor, details/, BuildStrategy, collective ops, NCCL
helpers) with GSPMD over `jax.sharding.Mesh`.
"""

from .mesh import (  # noqa: F401
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    current_mesh,
    make_mesh,
    mesh_guard,
    set_global_mesh,
    spec,
)
from .spmd import device_put_sharded, shard_program, spec_for  # noqa: F401
from .transpiler import (  # noqa: F401
    GradAllReduce,
    LocalSGD,
    ShardedWeightUpdate,
)
from .pipeline import PipelineOptimizer  # noqa: F401  (registers pipeline_block)
from .pipeline_uniform import (  # noqa: F401  (registers pipeline_uniform)
    append_outside_grad_allreduce,
    gate_loss,
    uniform_pipeline,
)
from .sparse import (  # noqa: F401
    quantize_embedding_grads,
    shard_sparse_tables,
    sparse_table_names,
)
