"""BERT — transformer encoder flagship (BERT-base benchmark in BASELINE.md).

Built entirely on the public layers API; equivalent in coverage to the
reference's ERNIE/BERT workloads (its fused ops multihead_matmul
operators/fused/multihead_matmul_op.cu and bert_encoder_functor.cu exist
only because CUDA needed hand fusion — on TPU, XLA fuses the unfused graph,
so the model is written in plain ops).

Tensor-parallel ready: every projection weight has a deterministic name, and
`bert_tp_shardings` returns Megatron-style GSPMD annotations over the "mp"
mesh axis (column-parallel QKV / FFN-in, row-parallel attn-out / FFN-out),
consumed by the executor's gspmd mode (parallel/spmd.py:wrap_gspmd).
"""

from __future__ import annotations

import math

from .. import layers
from ..param_attr import ParamAttr


class BertConfig:
    def __init__(
        self,
        vocab_size=30522,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        intermediate_size=3072,
        max_position=512,
        type_vocab_size=2,
        hidden_dropout=0.1,
        attention_dropout=0.1,
        initializer_range=0.02,
        use_fused_attention=True,
        use_fused_residual=True,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attention_dropout = attention_dropout
        self.initializer_range = initializer_range
        # one fused attention op (Pallas flash kernel on TPU) vs composed
        # matmul/softmax/dropout ops. The composed path is what TP/gspmd
        # sharding tests exercise; the fused op itself degrades to the same
        # math when the kernel cannot run (see ops/fused.py).
        self.use_fused_attention = use_fused_attention
        # one fused op for the residual tail LN(x + dropout(y)) — the
        # Pallas kernel in kernels/fused_residual.py; the composed path
        # stays for gspmd sharding propagation tests
        self.use_fused_residual = use_fused_residual

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        """For tests / dry runs: 2 layers, 128 hidden."""
        return cls(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            intermediate_size=512, max_position=128,
        )


def _init(cfg):
    from ..initializer import Normal

    return Normal(0.0, cfg.initializer_range)


def _dense(x, size, name, cfg, act=None):
    return layers.fc(
        x,
        size=size,
        num_flatten_dims=2,
        act=act,
        param_attr=ParamAttr(name=f"{name}_w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name=f"{name}_b"),
    )


def _attention(x, attn_bias, cfg, prefix, is_test):
    b, s, h = x.shape
    nh, dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    qkv = _dense(x, 3 * h, f"{prefix}_qkv", cfg)  # [B,S,3H] one fused matmul
    if cfg.use_fused_attention:
        # one op straight off the qkv matmul: the Pallas flash kernel
        # indexes the packed [B,S,3H] projection in place (no head-split
        # transposes, no [B,nh,S,S] probs in HBM); attn_bias is the [B,S]
        # key mask (0 keep / -1e4 pad)
        ctxv = layers.fused_qkv_attention(
            qkv, nh, key_bias=attn_bias,
            scale=1.0 / math.sqrt(dh),
            dropout_prob=cfg.attention_dropout, is_test=is_test,
        )
        return _dense(ctxv, h, f"{prefix}_out", cfg)

    # dense path: slice along the feature dim + per-tensor [B,nh,S,dh]
    # transposes (XLA folds the slices into the producing matmul and fuses
    # the transposes with their consuming dots)
    def head(t):
        return layers.transpose(layers.reshape(t, [b, s, nh, dh]), [0, 2, 1, 3])

    q = head(layers.slice(qkv, [2], [0], [h]))
    k = head(layers.slice(qkv, [2], [h], [2 * h]))
    v = head(layers.slice(qkv, [2], [2 * h], [3 * h]))
    bias4 = None
    if attn_bias is not None:
        bias4 = layers.reshape(attn_bias, [b, 1, 1, s])
    scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / math.sqrt(dh))
    if bias4 is not None:
        scores = scores + bias4  # [B,1,1,S] additive mask broadcast
    probs = layers.softmax(scores, axis=-1)
    probs = layers.dropout(
        probs, dropout_prob=cfg.attention_dropout, is_test=is_test
    )
    ctxv = layers.matmul(probs, v)  # [B,nh,S,dh]
    ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
    ctxv = layers.reshape(ctxv, [b, s, h])
    return _dense(ctxv, h, f"{prefix}_out", cfg)


def _residual_ln(x, branch, cfg, ln_name, is_test):
    """LN(x + dropout(branch)): one fused op (Pallas residual-tail kernel)
    or the composed dropout/add/layer_norm ops — same math, same param
    names either way."""
    if cfg.use_fused_residual:
        return layers.fused_dropout_add_ln(
            x, branch, cfg.hidden_dropout, is_test=is_test,
            param_attr=ParamAttr(name=f"{ln_name}_scale"),
            bias_attr=ParamAttr(name=f"{ln_name}_bias"),
        )
    branch = layers.dropout(branch, cfg.hidden_dropout, is_test=is_test)
    return layers.layer_norm(
        x + branch,
        begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{ln_name}_scale"),
        bias_attr=ParamAttr(name=f"{ln_name}_bias"),
    )


def _encoder_layer(x, attn_bias, cfg, prefix, is_test):
    attn = _attention(x, attn_bias, cfg, f"{prefix}_attn", is_test)
    x = _residual_ln(x, attn, cfg, f"{prefix}_ln1", is_test)
    # tanh-approximate GELU (the original BERT implementation's formula).
    # On TPU the exact erf lowers to a long VPU polynomial — profiled at
    # ~0.77 ms/layer fwd on [32,512,3072] (BASELINE.md round 4); tanh is
    # the canonical-and-cheaper form.
    ffn = _dense(x, cfg.intermediate_size, f"{prefix}_ffn_in", cfg)
    ffn = layers.gelu(ffn, approximate=True)
    ffn = _dense(ffn, cfg.hidden_size, f"{prefix}_ffn_out", cfg)
    return _residual_ln(x, ffn, cfg, f"{prefix}_ln2", is_test)


def _attn_bias(input_mask):
    """[B,S] float mask -> additive key-side attention bias [B,S]
    (0 keep, -1e4 mask; bf16-safe). Kept 2-D: the fused attention op takes
    the key bias directly, the dense path reshapes to [B,1,1,S]."""
    return layers.scale(input_mask, scale=1e4, bias=-1e4)


def bert_encoder_layers(x, input_mask, cfg, start=0, end=None, is_test=False,
                        checkpoints=None):
    """Run encoder layers [start, end) over [B,S,H] input — the unit of
    pipeline-stage splitting (device_guard slices the layer stack).
    `checkpoints`: optional list collecting per-layer outputs for
    RecomputeOptimizer segment boundaries."""
    attn_bias = _attn_bias(input_mask)
    end = cfg.num_layers if end is None else end
    for i in range(start, end):
        x = _encoder_layer(x, attn_bias, cfg, f"bert_l{i}", is_test)
        if checkpoints is not None:
            checkpoints.append(x)
    return x


def bert_encoder(input_ids, token_type_ids, input_mask, cfg, is_test=False,
                 num_layers=None, checkpoints=None):
    """input_ids/token_type_ids: [B,S] int64; input_mask: [B,S] float32.
    Returns sequence output [B,S,H]. num_layers limits the stack (pipeline
    stage 0 = embeddings + first half; see bert_encoder_layers)."""
    b, s = input_ids.shape
    word_emb = layers.embedding(
        input_ids,
        size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="word_embedding", initializer=_init(cfg)),
    )
    pos_ids = layers.reshape(
        layers.range(0, s, 1, "int64"), [1, s]
    )
    pos_emb = layers.embedding(
        pos_ids,
        size=[cfg.max_position, cfg.hidden_size],
        param_attr=ParamAttr(name="pos_embedding", initializer=_init(cfg)),
    )
    type_emb = layers.embedding(
        token_type_ids,
        size=[cfg.type_vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="type_embedding", initializer=_init(cfg)),
    )
    emb = word_emb + pos_emb + type_emb
    emb = layers.layer_norm(
        emb,
        begin_norm_axis=2,
        param_attr=ParamAttr(name="emb_ln_scale"),
        bias_attr=ParamAttr(name="emb_ln_bias"),
    )
    emb = layers.dropout(emb, cfg.hidden_dropout, is_test=is_test)
    n = cfg.num_layers if num_layers is None else num_layers
    return bert_encoder_layers(
        emb, input_mask, cfg, 0, n, is_test, checkpoints=checkpoints
    )


def bert_mlm_head(seq, mlm_labels, cfg):
    """Masked-LM loss head over [B,S,H] sequence output; mlm_labels [B,S]
    int64 with ignore_index -100 on unmasked positions."""
    b, s, h = seq.shape
    seq2 = layers.reshape(seq, [b * s, h])
    logits = layers.fc(
        seq2,
        size=cfg.vocab_size,
        param_attr=ParamAttr(name="mlm_out_w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name="mlm_out_b"),
    )
    labels = layers.reshape(mlm_labels, [b * s, 1])
    loss = layers.softmax_with_cross_entropy(logits, labels, ignore_index=-100)
    # average over the *masked* positions only: ignored positions contribute
    # zero loss, so a plain mean would scale loss/grads by the masking ratio.
    # [1]-shaped constant broadcasts, so the head stays batch-size agnostic
    # (pipeline microbatching shrinks the runtime batch)
    ignore = layers.fill_constant([1], "int64", -100)
    valid = layers.cast(layers.not_equal(labels, ignore), "float32")
    denom = layers.elementwise_max(
        layers.reduce_sum(valid), layers.fill_constant([1], "float32", 1.0)
    )
    return layers.elementwise_div(layers.reduce_sum(loss), denom)


def bert_mlm_head_gather(seq, mask_pos, mask_labels, cfg):
    """MLM head over the MASKED positions only (the reference's BERT
    pretraining gathers mask_pos before the vocab projection — the
    standard formulation; computing [B*S, V] logits wastes ~85% of the
    head FLOPs). mask_pos: [P] int32 indices into the flattened [B*S]
    sequence (padded entries point at any row with label -100);
    mask_labels: [P] vocab ids with -100 padding."""
    b, s, h = seq.shape
    seq2 = layers.reshape(seq, [b * s, h])
    picked = layers.gather(seq2, mask_pos)  # [P, h]
    logits = layers.fc(
        picked,
        size=cfg.vocab_size,
        param_attr=ParamAttr(name="mlm_out_w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name="mlm_out_b"),
    )
    labels = layers.reshape(mask_labels, [-1, 1])
    loss = layers.softmax_with_cross_entropy(logits, labels, ignore_index=-100)
    ignore = layers.fill_constant([1], "int64", -100)
    valid = layers.cast(layers.not_equal(labels, ignore), "float32")
    denom = layers.elementwise_max(
        layers.reduce_sum(valid), layers.fill_constant([1], "float32", 1.0)
    )
    return layers.elementwise_div(layers.reduce_sum(loss), denom)


def bert_pretrain(input_ids, token_type_ids, input_mask, mlm_labels, cfg,
                  is_test=False, checkpoints=None, mask_pos=None):
    """End-to-end MLM pretraining loss (encoder + head). With mask_pos
    [P], mlm_labels is the gathered [P] label vector and the vocab
    projection runs only on masked rows (reference mask_pos contract)."""
    seq = bert_encoder(
        input_ids, token_type_ids, input_mask, cfg, is_test,
        checkpoints=checkpoints,
    )
    if mask_pos is not None:
        return bert_mlm_head_gather(seq, mask_pos, mlm_labels, cfg)
    return bert_mlm_head(seq, mlm_labels, cfg)


def bert_tp_shardings(cfg, axis="mp"):
    """Megatron-style tensor-parallel GSPMD annotations for every encoder
    layer: QKV & FFN-in column-parallel (shard output features), attn-out &
    FFN-out row-parallel (shard input features); XLA propagation inserts the
    reduce where row-parallel outputs merge. Vocab-sharded embedding/MLM head
    included (vocab dim over `axis`)."""
    sh = {
        "word_embedding": (axis, None),
        "mlm_out_w": (None, axis),
    }
    for i in range(cfg.num_layers):
        p = f"bert_l{i}"
        sh[f"{p}_attn_qkv_w"] = (None, axis)
        sh[f"{p}_attn_qkv_b"] = (axis,)
        sh[f"{p}_attn_out_w"] = (axis, None)
        sh[f"{p}_ffn_in_w"] = (None, axis)
        sh[f"{p}_ffn_in_b"] = (axis,)
        sh[f"{p}_ffn_out_w"] = (axis, None)
    return sh
