"""Composed-parallelism BERT/ERNIE pretraining: dp × mp × pp + recompute +
AMP + vocab-sharded embeddings in ONE program.

This is the ERNIE-3.0-style "stack everything" configuration
(BASELINE.json configs[4]). The reference reaches it by meta-optimizer
stacking — RecomputeOptimizer (optimizer.py:3858) wrapped by the AMP
decorator (contrib/mixed_precision/decorator.py:218) wrapped by
PipelineOptimizer (optimizer.py:3556), wrapped by CollectiveOptimizer
(incubate/fleet/collective/__init__.py:384) which adds the dp transpile —
each strategy a separate NCCL/program rewrite that must be composed by
hand.

TPU-native composition is the same optimizer stack but ONE jitted SPMD
program over a 3-axis mesh in "hybrid" shard_map mode
(parallel/spmd.py):
  * pp — manual axis: the GPipe scheduler (lax.scan + ppermute over ICI)
    needs lax.axis_index and explicit neighbor sends;
  * dp — manual axis: grad allreduce ops from GradAllReduce
    (parallel/transpiler.py) ride lax.psum;
  * mp — gspmd-Auto axis: Megatron column/row-parallel weights carry
    sharding annotations (bert_tp_shardings) and the XLA SPMD partitioner
    inserts the row-parallel reduce — no hand-written TP collectives;
  * recompute — stage sub-blocks fold per-layer segments into
    jax.checkpoint (incubate/recompute.py), so activations are
    rematerialized in backward;
  * AMP — bf16 cast rewrite recurses into the stage sub-blocks and the
    pipeline boundary itself rides ICI in bf16
    (contrib/mixed_precision/fp16_utils.py).

The input word embedding and MLM output projection are vocab-sharded over
mp (the "sharded table for the input layer"), so the largest tables never
materialize replicated.
"""

from __future__ import annotations

import numpy as np

from .bert import (BertConfig, bert_encoder, bert_encoder_layers,
                   bert_mlm_head, bert_tp_shardings)


def build_bert_3d(cfg, batch, seq_len, *, num_stages=2, microbatches=2,
                  dp=1, use_amp=True, use_recompute=True, lr=1e-4,
                  seed=1234, pipeline_mode="uniform"):
    """Build the composed program. `batch` is the PER-DP-SHARD batch (each
    dp group feeds its own slice); it must divide by `microbatches`.

    pipeline_mode:
      * "uniform" (default) — the stage-uniform pipeline
        (parallel/pipeline_uniform.py): stacked per-stage weights sharded
        over pp, branch-free body. The ONLY mode that composes with
        gspmd-Auto tensor parallelism: the lax.switch dispatch of
        "blocks" mode puts partitioner-inserted mp collectives inside
        device-dependent branches, which deadlocks any mesh (see the
        pipeline_uniform module docstring). Also the only mode where
        params/optimizer state shard by stage (HBM /K).
      * "blocks" — the reference-parity heterogeneous PipelineOptimizer
        (device_guard-cut stages). Valid for pp×dp; do NOT combine with
        mp shardings.

    Returns (main, startup, loss). To run it sharded:

        mesh = make_mesh({"dp": dp, "mp": mp, "pp": num_stages}, devices)
        shard_program(main, mesh, bert_3d_shardings(cfg, num_stages),
                      mode="hybrid", manual_axes=("dp", "pp"))

    Meshless, the same program degrades to valid single-device numerics
    (collectives are identity, the pipeline runs its sequential-microbatch
    path) — which is what the equivalence tests compare against.
    """
    if pipeline_mode == "uniform":
        return _build_uniform(
            cfg, batch, seq_len, num_stages=num_stages,
            microbatches=microbatches, dp=dp, use_amp=use_amp,
            use_recompute=use_recompute, lr=lr, seed=seed,
        )
    import paddle_tpu as fluid
    from paddle_tpu.contrib.mixed_precision import decorate
    from paddle_tpu.incubate import RecomputeOptimizer
    from paddle_tpu.optimizer import Adam
    from paddle_tpu.parallel.transpiler import GradAllReduce

    if batch % microbatches:
        raise ValueError(
            f"per-shard batch {batch} must divide by microbatches "
            f"{microbatches}"
        )
    if cfg.num_layers < num_stages:
        raise ValueError(
            f"{cfg.num_layers} layers cannot fill {num_stages} stages"
        )

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [batch, seq_len], "int64")
        types = fluid.data("types", [batch, seq_len], "int64")
        mask = fluid.data("mask", [batch, seq_len], "float32")
        labels = fluid.data("labels", [batch, seq_len], "int64")

        # layer stack split contiguously across stages; embeddings live on
        # stage 0, the MLM head on the last stage (reference ERNIE
        # device_guard placement)
        per = [cfg.num_layers // num_stages] * num_stages
        for i in range(cfg.num_layers % num_stages):
            per[i] += 1
        checkpoints = []
        with fluid.device_guard("pipeline:0"):
            h = bert_encoder(ids, types, mask, cfg, num_layers=per[0],
                             checkpoints=checkpoints)
            if num_stages == 1:
                loss = bert_mlm_head(h, labels, cfg)
        start = per[0]
        for st in range(1, num_stages):
            with fluid.device_guard(f"pipeline:{st}"):
                h = bert_encoder_layers(
                    h, mask, cfg, start=start, end=start + per[st],
                    checkpoints=checkpoints,
                )
                start += per[st]
                if st == num_stages - 1:
                    loss = bert_mlm_head(h, labels, cfg)

        inner = Adam(lr)
        if use_recompute:
            inner = RecomputeOptimizer(inner)
            # per-encoder-layer boundaries; the LAST checkpoint of each
            # stage is that stage's pipeline boundary (protected output)
            inner._set_checkpoints(checkpoints)
        if use_amp:
            # bf16: same exponent range as fp32, static unit scale; the
            # finiteness check still zeroes grads on a bad step
            inner = decorate(inner, use_dynamic_loss_scaling=False,
                             init_loss_scaling=1.0, dest_dtype="bfloat16")
        if num_stages > 1:
            from paddle_tpu.parallel import PipelineOptimizer

            pipe = PipelineOptimizer(inner, num_microbatches=microbatches,
                                     axis_name="pp")
            _, params_grads = pipe.minimize(loss, startup)
        else:
            # no pipeline: dp×mp (+ recompute + AMP) only
            _, params_grads = inner.minimize(loss, startup)

        if dp > 1:
            GradAllReduce(dp, axis_name="dp").transpile(main, params_grads)
            blk = main.global_block
            # fetched loss is the shard-local mean; average across dp
            blk.append_op(
                "scale", {"X": [loss.name]}, {"Out": [loss.name]},
                {"scale": 1.0 / dp, "bias": 0.0},
            )
            blk.append_op(
                "c_allreduce_sum", {"X": [loss.name]}, {"Out": [loss.name]},
                {"axis_name": "dp"},
            )
    return main, startup, loss


def _build_uniform(cfg, batch, seq_len, *, num_stages, microbatches, dp,
                   use_amp, use_recompute, lr, seed):
    import paddle_tpu as fluid
    from paddle_tpu.contrib.mixed_precision import decorate
    from paddle_tpu.optimizer import Adam
    from paddle_tpu.parallel import (append_outside_grad_allreduce,
                                     gate_loss, uniform_pipeline)
    from paddle_tpu.parallel.transpiler import GradAllReduce

    if batch % microbatches:
        raise ValueError(
            f"per-shard batch {batch} must divide by microbatches "
            f"{microbatches}"
        )
    if cfg.num_layers % num_stages:
        raise ValueError(
            f"{cfg.num_layers} layers must divide evenly across "
            f"{num_stages} uniform stages"
        )
    layers_per_stage = cfg.num_layers // num_stages

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [batch, seq_len], "int64")
        types = fluid.data("types", [batch, seq_len], "int64")
        mask = fluid.data("mask", [batch, seq_len], "float32")
        labels = fluid.data("labels", [batch, seq_len], "int64")

        # embeddings (vocab-shardable over mp) run unpipelined on every
        # device; the uniform layer stack is the pipelined region
        emb = bert_encoder(ids, types, mask, cfg, num_layers=0)

        def stage(x_in):
            return bert_encoder_layers(
                x_in, mask, cfg, start=0, end=layers_per_stage
            )

        if num_stages > 1:
            seq = uniform_pipeline(
                emb, stage, num_stages, microbatches, mb_extern=[mask],
                axis_name="pp", remat=use_recompute,
            )
        else:
            seq = stage(emb)
        raw_loss = bert_mlm_head(seq, labels, cfg)
        loss = (
            gate_loss(raw_loss, "pp") if num_stages > 1 else raw_loss
        )

        inner = Adam(lr)
        if use_amp:
            inner = decorate(inner, use_dynamic_loss_scaling=False,
                             init_loss_scaling=1.0, dest_dtype="bfloat16")
        _, params_grads = inner.minimize(loss, startup)
        if num_stages > 1:
            append_outside_grad_allreduce(main, params_grads, "pp")
        if dp > 1:
            GradAllReduce(dp, axis_name="dp").transpile(main, params_grads)
            blk = main.global_block
            blk.append_op(
                "scale", {"X": [loss.name]}, {"Out": [loss.name]},
                {"scale": 1.0 / dp, "bias": 0.0},
            )
            blk.append_op(
                "c_allreduce_sum", {"X": [loss.name]}, {"Out": [loss.name]},
                {"axis_name": "dp"},
            )
    return main, startup, loss


def bert_3d_shardings(cfg, num_stages=None, mp_axis="mp", dp_axis="dp",
                      pp_axis="pp"):
    """Sharding annotations for the composed program.

    num_stages set (uniform mode): encoder params are [K, ...] stacks named
    `bert_l{j}_*@STACK` — spec = (pp,) + the layer's Megatron TP spec, so
    one array is simultaneously stage-sharded (manual pp) and
    tensor-sharded (auto mp). Embedding/MLM head keep their vocab-mp
    shard; feeds shard over dp.

    num_stages None ("blocks" mode): per-layer params with TP specs only
    (every device holds all stages — the lax.switch design cannot shard by
    stage).

    Adam moments need no entries: same-shaped optimizer accumulators
    inherit their parameter's spec automatically (spec_for's _accum_of
    fallback, parallel/spmd.py) — the reference's sharded-optimizer
    analogue; beta-pow accumulators are scalars and stay replicated."""
    if num_stages is None:
        sh = bert_tp_shardings(cfg, axis=mp_axis)
    else:
        layers_per_stage = cfg.num_layers // num_stages
        import copy

        tcfg = copy.copy(cfg)
        tcfg.num_layers = layers_per_stage
        tp = bert_tp_shardings(tcfg, axis=mp_axis)
        sh = {}
        for p, spec in tp.items():
            if p.startswith("bert_l"):
                sh[f"{p}@STACK"] = (pp_axis,) + tuple(spec)
            else:
                sh[p] = spec
    for name in ("ids", "types", "mask", "labels"):
        sh[name] = (dp_axis,)
    return sh


def example_feed_3d(cfg, batch, seq_len, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "ids": rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(
            "int64"
        ),
        "types": rng.randint(
            0, cfg.type_vocab_size, (batch, seq_len)
        ).astype("int64"),
        "mask": np.ones((batch, seq_len), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(
            "int64"
        ),
    }
