"""GPT — decoder-only causal LM (the reference era's ERNIE-GEN/GPT-2
workloads; BASELINE.md lists ERNIE dygraph pretrain as the stretch
target). Pre-LN transformer decoder built on the public layers API; the
causal mask runs INSIDE the packed-QKV Pallas flash kernel (causal=True),
so no [B,nh,S,S] mask or probability tensor ever reaches HBM.

Tensor-parallel ready like models/bert.py: deterministic parameter names +
`gpt_tp_shardings` Megatron annotations over the "mp" axis.
"""

from __future__ import annotations

import math

from .. import layers
from ..param_attr import ParamAttr


class GPTConfig:
    def __init__(
        self,
        vocab_size=50257,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        intermediate_size=3072,
        max_position=1024,
        hidden_dropout=0.1,
        attention_dropout=0.1,
        initializer_range=0.02,
        use_fused_attention=True,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.hidden_dropout = hidden_dropout
        self.attention_dropout = attention_dropout
        self.initializer_range = initializer_range
        self.use_fused_attention = use_fused_attention

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(
            vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
            intermediate_size=256, max_position=128,
        )


def _init(cfg):
    from ..initializer import Normal

    return Normal(0.0, cfg.initializer_range)


def _dense(x, size, name, cfg, act=None):
    return layers.fc(
        x, size=size, num_flatten_dims=2, act=act,
        param_attr=ParamAttr(name=f"{name}_w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name=f"{name}_b"),
    )


def _ln(x, name):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}_scale"),
        bias_attr=ParamAttr(name=f"{name}_bias"),
    )


def _decoder_layer(x, cfg, prefix, is_test):
    b, s, h = x.shape
    nh, dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    # pre-LN attention block
    a = _ln(x, f"{prefix}_ln1")
    qkv = _dense(a, 3 * h, f"{prefix}_attn_qkv", cfg)
    if cfg.use_fused_attention:
        ctxv = layers.fused_qkv_attention(
            qkv, nh, causal=True, scale=1.0 / math.sqrt(dh),
            dropout_prob=cfg.attention_dropout, is_test=is_test,
        )
    else:
        def head(t):
            return layers.transpose(
                layers.reshape(t, [b, s, nh, dh]), [0, 2, 1, 3]
            )

        q = head(layers.slice(qkv, [2], [0], [h]))
        k = head(layers.slice(qkv, [2], [h], [2 * h]))
        v = head(layers.slice(qkv, [2], [2 * h], [3 * h]))
        scores = layers.matmul(
            q, k, transpose_y=True, alpha=1.0 / math.sqrt(dh)
        )
        # causal additive mask: 0 on/below the diagonal, -1e4 above
        mask = layers.reshape(
            layers.scale(
                layers.tril(layers.fill_constant([s, s], "float32", 1.0)),
                scale=1e4, bias=-1e4,
            ),
            [1, 1, s, s],
        )
        scores = scores + mask
        probs = layers.softmax(scores, axis=-1)
        probs = layers.dropout(
            probs, cfg.attention_dropout, is_test=is_test
        )
        ctxv = layers.reshape(
            layers.transpose(layers.matmul(probs, v), [0, 2, 1, 3]),
            [b, s, h],
        )
    attn = _dense(ctxv, h, f"{prefix}_attn_out", cfg)
    x = x + layers.dropout(attn, cfg.hidden_dropout, is_test=is_test)
    # pre-LN MLP block
    m = _ln(x, f"{prefix}_ln2")
    # tanh-approximate GELU — GPT-2's canonical formula, and ~2x cheaper
    # than exact erf on the TPU VPU (see models/bert.py)
    m = _dense(m, cfg.intermediate_size, f"{prefix}_mlp_in", cfg)
    m = layers.gelu(m, approximate=True)
    m = _dense(m, cfg.hidden_size, f"{prefix}_mlp_out", cfg)
    return x + layers.dropout(m, cfg.hidden_dropout, is_test=is_test)


def gpt_decoder(input_ids, cfg, is_test=False):
    """input_ids [B, S] int64 -> final hidden states [B, S, H]."""
    b, s = input_ids.shape
    tok = layers.embedding(
        input_ids, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="wte", initializer=_init(cfg)),
    )
    pos_ids = layers.reshape(layers.range(0, s, 1, "int64"), [1, s])
    pos = layers.embedding(
        pos_ids, size=[cfg.max_position, cfg.hidden_size],
        param_attr=ParamAttr(name="wpe", initializer=_init(cfg)),
    )
    x = layers.dropout(tok + pos, cfg.hidden_dropout, is_test=is_test)
    for i in range(cfg.num_layers):
        x = _decoder_layer(x, cfg, f"gpt_l{i}", is_test)
    return _ln(x, "gpt_lnf")


def _lm_head(hidden, cfg):
    """The (shared-name) vocab projection every GPT graph variant uses —
    one definition so the `lm_head_w` checkpoint contract cannot drift."""
    return layers.fc(
        hidden, cfg.vocab_size, num_flatten_dims=2, bias_attr=False,
        param_attr=ParamAttr(name="lm_head_w", initializer=_init(cfg)),
    )


def gpt_lm_loss(input_ids, cfg, is_test=False, labels=None):
    """Next-token LM loss; labels default to input_ids shifted left (the
    final position predicts nothing and is dropped)."""
    b, s = input_ids.shape
    hidden = gpt_decoder(input_ids, cfg, is_test=is_test)
    # slice the HIDDEN states, not the logits: slicing after the vocab
    # projection copies a [B, S, V] tensor (~0.5 GB at S=2048/V=32k);
    # slicing before it is a [B, S, H] copy and the head matmul computes
    # only the s-1 predicted positions
    pred_h = layers.slice(hidden, [1], [0], [s - 1])
    pred = _lm_head(pred_h, cfg)
    if labels is None:
        tgt = layers.slice(input_ids, [1], [1], [s])
    else:
        tgt = layers.slice(labels, [1], [1], [s])
    loss = layers.softmax_with_cross_entropy(
        layers.reshape(pred, [b * (s - 1), cfg.vocab_size]),
        layers.reshape(tgt, [b * (s - 1), 1]),
    )
    return layers.mean(loss)


def gpt_logits(input_ids, cfg, is_test=True):
    """Full-context logits [B, S, V] — the serving/full-recompute head
    (no label shift, no loss): every position's next-token distribution."""
    hidden = gpt_decoder(input_ids, cfg, is_test=is_test)
    return _lm_head(hidden, cfg)


# --- KV-cache serving graphs (prefill + single-token decode) ---------------
#
# Generation through the training graph re-runs the whole context every
# token (O(S) recompute per emitted token). The serving split keeps each
# layer's K/V rows in persistable scope vars shared BETWEEN two programs:
# a prefill program that embeds the full context once and fills the cache,
# and a single-token decode program that appends one K/V row and attends
# over the cache — O(1) recompute per token. Parameter names match
# gpt_decoder/gpt_logits exactly, so a trained checkpoint loads into
# either graph unchanged (serving/generate.py drives the pair).


def gpt_cache_names(cfg):
    """The persistable cache var names both serving programs share."""
    out = []
    for i in range(cfg.num_layers):
        out += [f"gpt_l{i}_cache_k", f"gpt_l{i}_cache_v"]
    return out


def _cache_var(name, batch, max_len, hidden):
    from ..framework.program import default_main_program

    blk = default_main_program().global_block
    if blk.has_var(name):
        return blk.var(name)
    return blk.create_var(
        name=name, shape=(batch, max_len, hidden), dtype="float32",
        persistable=True,
    )


def _cached_decoder_layer(x, cfg, prefix, write_pos, attend_pos, max_len):
    """Pre-LN decoder layer routed through the layer's KV cache: write this
    call's K/V rows at `write_pos`, attend Q over the cache up to
    `attend_pos` (inclusive). Dropout sites keep their test-mode
    ``downgrade_in_infer`` (1 - p) scaling so outputs match the training
    graph's ``is_test`` numerics (the freeze-parity contract)."""
    from ..framework.program import default_main_program
    from ..layers.tensor import _simple

    b, t, h = x.shape
    nh, dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    a = _ln(x, f"{prefix}_ln1")
    qkv = _dense(a, 3 * h, f"{prefix}_attn_qkv", cfg)
    q = layers.slice(qkv, [2], [0], [h])
    k = layers.slice(qkv, [2], [h], [2 * h])
    v = layers.slice(qkv, [2], [2 * h], [3 * h])
    ck = _cache_var(f"{prefix}_cache_k", b, max_len, h)
    cv = _cache_var(f"{prefix}_cache_v", b, max_len, h)
    blk = default_main_program().global_block
    for cache, rows in ((ck, k), (cv, v)):
        blk.append_op(
            "kv_cache_write",
            {"Cache": [cache.name], "X": [rows.name],
             "Pos": [write_pos.name]},
            {"Out": [cache.name]},
        )
    ctxv = _simple(
        "kv_cache_attention",
        {"Q": [q], "CacheK": [ck], "CacheV": [cv], "Pos": [attend_pos]},
        {"num_heads": nh, "scale": 1.0 / math.sqrt(dh),
         "prob_scale": 1.0 - cfg.attention_dropout},
    )
    attn = _dense(ctxv, h, f"{prefix}_attn_out", cfg)
    x = x + layers.dropout(attn, cfg.hidden_dropout, is_test=True)
    m = _ln(x, f"{prefix}_ln2")
    m = _dense(m, cfg.intermediate_size, f"{prefix}_mlp_in", cfg)
    m = layers.gelu(m, approximate=True)
    m = _dense(m, cfg.hidden_size, f"{prefix}_mlp_out", cfg)
    return x + layers.dropout(m, cfg.hidden_dropout, is_test=True)


def gpt_prefill(context_ids, cfg, max_len):
    """Prefill graph body: embed the full [B, S] context, fill every
    layer's KV cache rows 0..S-1, and return the LAST position's
    next-token logits [B, 1, V]. `max_len` bounds the cache (must cover
    context + generated tokens; <= cfg.max_position)."""
    b, s = context_ids.shape
    if max_len > cfg.max_position:
        from ..errors import InvalidArgumentError

        raise InvalidArgumentError(
            f"max_len {max_len} exceeds cfg.max_position {cfg.max_position}"
        )
    tok = layers.embedding(
        context_ids, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="wte", initializer=_init(cfg)),
    )
    pos_ids = layers.reshape(layers.range(0, s, 1, "int64"), [1, s])
    pos = layers.embedding(
        pos_ids, size=[cfg.max_position, cfg.hidden_size],
        param_attr=ParamAttr(name="wpe", initializer=_init(cfg)),
    )
    x = layers.dropout(tok + pos, cfg.hidden_dropout, is_test=True)
    write_pos = layers.fill_constant([1], "int32", 0)
    attend_pos = layers.fill_constant([1], "int32", s - 1)
    for i in range(cfg.num_layers):
        x = _cached_decoder_layer(
            x, cfg, f"gpt_l{i}", write_pos, attend_pos, max_len
        )
    x = _ln(x, "gpt_lnf")
    last_h = layers.slice(x, [1], [s - 1], [s])
    return _lm_head(last_h, cfg)


def gpt_decode_step(token_ids, pos_ids, cfg, max_len):
    """Single-token decode graph body: embed the [B, 1] token at position
    `pos_ids` ([1, 1] int64 feed), append its K/V rows to every layer's
    cache at that position, attend over the cache, and return next-token
    logits [B, 1, V]. Run repeatedly with the SAME shapes — one compiled
    executable serves the whole generation."""
    b = token_ids.shape[0]
    # [B, 1] ids hit the v1 lookup_table (trailing-1 squeeze): restore the
    # [B, T=1, H] layout the layer stack expects
    tok = layers.reshape(
        layers.embedding(
            token_ids, size=[cfg.vocab_size, cfg.hidden_size],
            param_attr=ParamAttr(name="wte", initializer=_init(cfg)),
        ),
        [b, 1, cfg.hidden_size],
    )
    pos = layers.reshape(
        layers.embedding(
            pos_ids, size=[cfg.max_position, cfg.hidden_size],
            param_attr=ParamAttr(name="wpe", initializer=_init(cfg)),
        ),
        [1, 1, cfg.hidden_size],
    )
    x = layers.dropout(tok + pos, cfg.hidden_dropout, is_test=True)
    for i in range(cfg.num_layers):
        x = _cached_decoder_layer(
            x, cfg, f"gpt_l{i}", pos_ids, pos_ids, max_len
        )
    x = _ln(x, "gpt_lnf")
    return _lm_head(x, cfg)


def gpt_tp_shardings(cfg, axis="mp"):
    """Megatron column/row-parallel annotations (see bert_tp_shardings)."""
    sh = {"wte": (axis, None), "lm_head_w": (None, axis)}
    for i in range(cfg.num_layers):
        p = f"gpt_l{i}"
        sh[f"{p}_attn_qkv_w"] = (None, axis)
        sh[f"{p}_attn_qkv_b"] = (axis,)
        sh[f"{p}_attn_out_w"] = (axis, None)
        sh[f"{p}_mlp_in_w"] = (None, axis)
        sh[f"{p}_mlp_in_b"] = (axis,)
        sh[f"{p}_mlp_out_w"] = (axis, None)
    return sh
