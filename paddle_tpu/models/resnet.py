"""ResNet v1.5 (18/34/50/101/152) — the image-classification flagship.

Capability parity with the reference's book-test image classification model
(python/paddle/fluid/tests/book/test_image_classification.py) scaled to the
ResNet-50 ImageNet benchmark config in BASELINE.md. TPU notes:
  * NCHW layout at the API (fluid parity); XLA re-layouts for the MXU.
  * conv + batch_norm pairs fuse in XLA (the reference needed
    conv_bn_fuse_pass, ir/conv_bn_fuse_pass.cc — here it is free).
"""

from __future__ import annotations

from .. import layers

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None,
             is_test=False, padding=None):
    conv = layers.conv2d(
        x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2 if padding is None else padding,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(x, num_filters, stride, is_test):
    if x.shape[1] != num_filters or stride != 1:
        return _conv_bn(x, num_filters, 1, stride, is_test=is_test)
    return x


def _basic_block(x, num_filters, stride, is_test):
    y = _conv_bn(x, num_filters, 3, stride, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters, 3, 1, is_test=is_test)
    short = _shortcut(x, num_filters, stride, is_test)
    return layers.relu(y + short)


def _bottleneck_block(x, num_filters, stride, is_test):
    y = _conv_bn(x, num_filters, 1, 1, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters, 3, stride, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters * 4, 1, 1, is_test=is_test)
    short = _shortcut(x, num_filters * 4, stride, is_test)
    return layers.relu(y + short)


def resnet(image, class_num=1000, depth=50, is_test=False,
           space_to_depth_stem=False):
    """Build ResNet; returns logits. image: NCHW float var.

    space_to_depth_stem: the standard TPU stem transform (MLPerf ResNet):
    the 7x7/s2 conv on 3 channels starves the MXU (contraction dim 3,
    stride-2 input walks); space-to-depth(2) turns the input into
    [N, 12, H/2, W/2] and an equivalent-function-class 4x4/s1 conv reads
    it densely. Trained from scratch (the 4x4x12 kernel subsumes the
    7x7x3 one at even alignments), so accuracy parity holds; checkpoints
    are NOT weight-compatible with the plain stem."""
    if depth not in _DEPTH_CFG:
        raise ValueError(f"unsupported depth {depth}; pick {sorted(_DEPTH_CFG)}")
    block_kind, counts = _DEPTH_CFG[depth]
    block = _basic_block if block_kind == "basic" else _bottleneck_block

    if space_to_depth_stem:
        x = layers.space_to_depth(image, blocksize=2)
        # SAME for the even 4-wide kernel needs asymmetric total pad 3
        # (symmetric (4-1)//2 would shrink the map to 111x111 and starve
        # the border pixels of full kernel support)
        x = layers.pad2d(x, paddings=[1, 2, 1, 2])
        x = _conv_bn(x, 64, 4, stride=1, act="relu", is_test=is_test,
                     padding=0)
    else:
        x = _conv_bn(image, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, n_blocks in enumerate(counts):
        for i in range(n_blocks):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block(x, num_filters[stage], stride, is_test)
    x = layers.pool2d(x, global_pooling=True, pool_type="avg")
    return layers.fc(x, size=class_num)


def resnet_train_net(image, label, depth=50, class_num=1000,
                     space_to_depth_stem=False):
    """logits -> (avg softmax-CE loss, top-1 accuracy)."""
    logits = resnet(image, class_num=class_num, depth=depth,
                    space_to_depth_stem=space_to_depth_stem)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(layers.softmax(logits), label)
    return avg_loss, acc
