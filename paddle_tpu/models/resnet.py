"""ResNet v1.5 (18/34/50/101/152) — the image-classification flagship.

Capability parity with the reference's book-test image classification model
(python/paddle/fluid/tests/book/test_image_classification.py) scaled to the
ResNet-50 ImageNet benchmark config in BASELINE.md. TPU notes:
  * NCHW layout at the API (fluid parity); XLA re-layouts for the MXU.
  * conv + batch_norm pairs fuse in XLA (the reference needed
    conv_bn_fuse_pass, ir/conv_bn_fuse_pass.cc — here it is free).
"""

from __future__ import annotations

from .. import layers

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None, is_test=False):
    conv = layers.conv2d(
        x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        bias_attr=False,
    )
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(x, num_filters, stride, is_test):
    if x.shape[1] != num_filters or stride != 1:
        return _conv_bn(x, num_filters, 1, stride, is_test=is_test)
    return x


def _basic_block(x, num_filters, stride, is_test):
    y = _conv_bn(x, num_filters, 3, stride, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters, 3, 1, is_test=is_test)
    short = _shortcut(x, num_filters, stride, is_test)
    return layers.relu(y + short)


def _bottleneck_block(x, num_filters, stride, is_test):
    y = _conv_bn(x, num_filters, 1, 1, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters, 3, stride, act="relu", is_test=is_test)
    y = _conv_bn(y, num_filters * 4, 1, 1, is_test=is_test)
    short = _shortcut(x, num_filters * 4, stride, is_test)
    return layers.relu(y + short)


def resnet(image, class_num=1000, depth=50, is_test=False):
    """Build ResNet; returns logits. image: NCHW float var."""
    if depth not in _DEPTH_CFG:
        raise ValueError(f"unsupported depth {depth}; pick {sorted(_DEPTH_CFG)}")
    block_kind, counts = _DEPTH_CFG[depth]
    block = _basic_block if block_kind == "basic" else _bottleneck_block

    x = _conv_bn(image, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, n_blocks in enumerate(counts):
        for i in range(n_blocks):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block(x, num_filters[stage], stride, is_test)
    x = layers.pool2d(x, global_pooling=True, pool_type="avg")
    return layers.fc(x, size=class_num)


def resnet_train_net(image, label, depth=50, class_num=1000):
    """logits -> (avg softmax-CE loss, top-1 accuracy)."""
    logits = resnet(image, class_num=class_num, depth=depth)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.reduce_mean(loss)
    acc = layers.accuracy(layers.softmax(logits), label)
    return avg_loss, acc
