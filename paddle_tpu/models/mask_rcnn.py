"""Mask R-CNN — ResNet-FPN backbone, RPN, Fast R-CNN box head, mask head.

Reference: the PaddleCV Mask R-CNN config named in BASELINE.json, built on
the reference ops rpn_target_assign / generate_proposals /
generate_proposal_labels / generate_mask_labels / distribute_fpn_proposals /
collect_fpn_proposals / roi_align (all per-op files under
paddle/fluid/operators/detection/, cited in ops/detection_ext.py).

TPU-native shape contract: every stage emits fixed-size tensors with
-1/0 padding and live counts, so the whole train step is ONE static XLA
computation — RPN losses gather sampled anchors with mode="fill", head
losses mask by label validity. GtSegms are dense per-gt bitmaps
(rasterization is the data pipeline's job).

Two train paths:

* ``mask_rcnn_train`` — the legacy single-image graph (batch = 1, the
  reference's LoD image walk). DEPRECATED for training throughput: B
  images need B unrolled copies of every detection op, and the r5
  BASELINE.md limiter analysis measured ~50-58 ms/image of device-busy
  small-op bookkeeping in exactly that unroll.
* ``mask_rcnn_train_batched`` — the r6 cross-image batched graph: images
  [B, 3, H, W] flow through the conv tower, heads, and the rank-lifted
  detection ops (ops/detection.py, ops/detection_ext.py) as single wide
  [B, ...] ops with fixed per-image RoI caps and validity masks. Losses
  are normalized per image then averaged, so B=1 reproduces the legacy
  losses exactly and the batched loss equals the mean of per-image
  losses up to sampling jitter (fp-order tolerance when caps saturate).

``batched_detection_enabled()`` reads the PADDLE_TPU_BATCHED_DETECTION
env knob (default on) — bench.py and builders use it to pick the path.
"""

from __future__ import annotations

import os

from .. import layers
from ..initializer import Normal
from ..layers import detection as det
from ..param_attr import ParamAttr


def batched_detection_enabled():
    """Env/config knob for the batched vs legacy per-image detection path
    (PADDLE_TPU_BATCHED_DETECTION, default on). The ops themselves
    dispatch on input rank; this only selects which graph builders and
    bench legs construct."""
    return os.environ.get(
        "PADDLE_TPU_BATCHED_DETECTION", "1"
    ).lower() not in ("0", "false", "off")


def _head_attr(std=0.01):
    """Detectron-style head init: small normal keeps initial RPN deltas and
    class logits near zero (Xavier on unnormalized FPN features otherwise
    emits O(30) deltas and the reg loss explodes)."""
    return ParamAttr(initializer=Normal(0.0, std))


class MaskRCNNConfig:
    def __init__(self, class_num=81, fpn_ch=256, resolution=14,
                 anchor_sizes=(32, 64, 128, 256), scale=1.0,
                 rpn_pre_nms=2000, rpn_post_nms=256,
                 batch_size_per_im=64, depth=50):
        self.class_num = class_num
        self.fpn_ch = max(8, int(fpn_ch * scale))
        self.resolution = resolution
        self.anchor_sizes = list(anchor_sizes)
        self.aspect_ratios = [0.5, 1.0, 2.0]
        self.scale = scale
        self.rpn_pre_nms = rpn_pre_nms
        self.rpn_post_nms = rpn_post_nms
        self.batch_size_per_im = batch_size_per_im
        self.depth = depth
        self.min_level, self.max_level = 2, 5

    def ch(self, n):
        return max(4, int(n * self.scale))

    @classmethod
    def tiny(cls, class_num=4):
        """1/8-width model on a shallow backbone for CPU tests/dry-runs."""
        return cls(class_num=class_num, scale=0.125, rpn_pre_nms=64,
                   rpn_post_nms=16, batch_size_per_im=16, resolution=7,
                   depth=18)


def _conv_bn(x, ch, k, stride, act, is_test, name):
    y = layers.conv2d(x, ch, k, stride=stride, padding=(k - 1) // 2,
                      bias_attr=False)
    return layers.batch_norm(y, act=act, is_test=is_test)


def resnet_fpn_backbone(image, cfg, is_test=False):
    """C2..C5 from a ResNet trunk, laterals + top-down into P2..P5."""
    blocks = {18: [2, 2, 2, 2], 50: [3, 4, 6, 3]}[cfg.depth]
    x = _conv_bn(image, cfg.ch(64), 7, 2, "relu", is_test, "stem")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    cs = []
    widths = [cfg.ch(64), cfg.ch(128), cfg.ch(256), cfg.ch(512)]
    for stage, n in enumerate(blocks):
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            y = _conv_bn(x, widths[stage], 3, stride, "relu", is_test,
                         f"s{stage}b{i}a")
            y = _conv_bn(y, widths[stage], 3, 1, None, is_test,
                         f"s{stage}b{i}b")
            if x.shape[1] != widths[stage] or stride != 1:
                x = _conv_bn(x, widths[stage], 1, stride, None, is_test,
                             f"s{stage}b{i}s")
            x = layers.relu(y + x)
        cs.append(x)
    # FPN top-down (fpn in the reference's PaddleCV config)
    laterals = [layers.conv2d(c, cfg.fpn_ch, 1) for c in cs]  # C2..C5
    ps = [None] * 4
    ps[3] = laterals[3]
    for i in (2, 1, 0):
        up = layers.resize_nearest(ps[i + 1], scale=2.0)
        ps[i] = laterals[i] + up
    ps = [layers.conv2d(p, cfg.fpn_ch, 3, padding=1) for p in ps]
    return ps  # [P2, P3, P4, P5], strides 4, 8, 16, 32


def rpn_heads(ps, cfg):
    """Shared RPN head over FPN levels: per level (scores, deltas,
    anchors, variances)."""
    outs = []
    A = len(cfg.aspect_ratios)
    for lvl, p in enumerate(ps):
        h = layers.conv2d(p, cfg.fpn_ch, 3, padding=1, act="relu",
                          param_attr=_head_attr())
        scores = layers.conv2d(h, A, 1, act="sigmoid",
                               param_attr=_head_attr())
        deltas = layers.conv2d(h, 4 * A, 1, param_attr=_head_attr(0.001))
        anchors, variances = det.anchor_generator(
            p,
            anchor_sizes=[cfg.anchor_sizes[lvl]],
            aspect_ratios=cfg.aspect_ratios,
            stride=[2 ** (lvl + 2), 2 ** (lvl + 2)],
        )
        outs.append((scores, deltas, anchors, variances))
    return outs


def _rpn_losses(rpn_outs, gt_boxes, is_crowd, im_info, cfg):
    """Concat all levels' anchors/scores/deltas, one target assignment."""
    all_scores, all_deltas, all_anchors = [], [], []
    for scores, deltas, anchors, _ in rpn_outs:
        A = len(cfg.aspect_ratios)
        s = layers.reshape(layers.transpose(scores, [0, 2, 3, 1]), [-1, 1])
        d = layers.reshape(layers.transpose(deltas, [0, 2, 3, 1]), [-1, 4])
        a = layers.reshape(anchors, [-1, 4])
        all_scores.append(s)
        all_deltas.append(d)
        all_anchors.append(a)
    scores = layers.concat(all_scores, axis=0)  # [A_tot, 1]
    deltas = layers.concat(all_deltas, axis=0)  # [A_tot, 4]
    anchors = layers.concat(all_anchors, axis=0)  # [A_tot, 4]

    loc_idx, score_idx, tgt_label, tgt_bbox, bbox_w = det.rpn_target_assign(
        anchors, gt_boxes, is_crowd=is_crowd, im_info=im_info,
        rpn_batch_size_per_im=cfg.batch_size_per_im,
    )
    # sampled-score CE: gather(scores, score_idx), -1 rows masked
    samp_score = layers.gather(scores, layers.relu(score_idx))
    label_f = layers.cast(tgt_label, "float32")
    valid = layers.cast(
        layers.greater_equal(
            layers.cast(tgt_label, "float32"),
            layers.fill_constant([1], "float32", 0.0),
        ),
        "float32",
    )
    eps = 1e-6
    p = layers.clip(samp_score, eps, 1.0 - eps)
    ce = (0.0 - (label_f * layers.log(p)
                 + (1.0 - label_f) * layers.log(1.0 - p))) * valid
    cls_loss = layers.reduce_sum(ce) / (layers.reduce_sum(valid) + 1.0)

    samp_delta = layers.gather(deltas, layers.relu(loc_idx))
    reg_valid = layers.reshape(
        layers.cast(
            layers.greater_equal(
                layers.cast(loc_idx, "float32"),
                layers.fill_constant([1], "float32", 0.0),
            ),
            "float32",
        ),
        [-1, 1],
    )
    diff = (samp_delta - tgt_bbox) * bbox_w
    reg = layers.reduce_sum(layers.abs(diff), dim=1, keep_dim=True)
    reg_loss = layers.reduce_sum(reg * reg_valid) / (
        layers.reduce_sum(reg_valid) + 1.0
    )
    return cls_loss, reg_loss


def _fpn_roi_extract(ps, rois, cfg, resolution):
    """distribute rois over levels, roi_align each, restore order."""
    multi_rois, restore, _nums = det.distribute_fpn_proposals(
        rois, cfg.min_level, cfg.max_level, 4, 224,
    )
    feats = []
    for lvl, (p, r) in enumerate(zip(ps, multi_rois)):
        f = det.roi_align(
            p, r, pooled_height=resolution, pooled_width=resolution,
            spatial_scale=1.0 / (2 ** (lvl + 2)), sampling_ratio=2,
        )
        feats.append(f)
    stacked = layers.concat(feats, axis=0)  # level-major order
    # restore[i] = packed position of input roi i (-1 for dead rois ->
    # gather clamps to row 0; dead rows are masked by the losses)
    return layers.gather(stacked, layers.relu(restore))


def box_head(feat, cfg):
    flat = layers.reshape(feat, [feat.shape[0], -1])
    h = layers.fc(flat, cfg.ch(1024), act="relu", param_attr=_head_attr())
    h = layers.fc(h, cfg.ch(1024), act="relu", param_attr=_head_attr())
    cls_score = layers.fc(h, cfg.class_num, param_attr=_head_attr())
    bbox_pred = layers.fc(h, 4 * cfg.class_num, param_attr=_head_attr(0.001))
    return cls_score, bbox_pred


def mask_head(feat, cfg):
    h = feat
    for _ in range(4):
        h = layers.conv2d(h, cfg.fpn_ch, 3, padding=1, act="relu")
    h = layers.conv2d_transpose(h, cfg.fpn_ch, 2, stride=2, act="relu")
    return layers.conv2d(h, cfg.class_num, 1)  # [R, C, 2M, 2M] logits


def mask_rcnn_train(image, gt_boxes, gt_classes, is_crowd, gt_segms,
                    im_info, cfg=None):
    """One-image train graph; returns (total, rpn_cls, rpn_reg, head_cls,
    head_reg, mask) losses."""
    cfg = cfg or MaskRCNNConfig()
    ps = resnet_fpn_backbone(image, cfg, is_test=False)
    rpn_outs = rpn_heads(ps, cfg)
    rpn_cls_loss, rpn_reg_loss = _rpn_losses(
        rpn_outs, gt_boxes, is_crowd, im_info, cfg
    )

    # proposals per level -> collect
    lvl_rois, lvl_scores, lvl_nums = [], [], []
    for scores, deltas, anchors, variances in rpn_outs:
        rois, probs, nums = det.generate_proposals(
            scores, deltas, im_info, anchors, variances,
            pre_nms_top_n=cfg.rpn_pre_nms, post_nms_top_n=cfg.rpn_post_nms,
            nms_thresh=0.7, min_size=0.0,
        )
        lvl_rois.append(layers.reshape(rois, [-1, 4]))
        lvl_scores.append(layers.reshape(probs, [-1, 1]))
        lvl_nums.append(nums)
    rois, rois_num = det.collect_fpn_proposals(
        lvl_rois, lvl_scores, cfg.min_level, cfg.max_level,
        post_nms_top_n=cfg.rpn_post_nms, rois_nums=lvl_nums,
    )

    (rois, labels, bbox_targets, bbox_iw, bbox_ow, _num,
     _ov) = det.generate_proposal_labels(
        rois, gt_classes, is_crowd, gt_boxes, im_info,
        batch_size_per_im=cfg.batch_size_per_im,
        class_nums=cfg.class_num,
    )

    feat = _fpn_roi_extract(ps, rois, cfg, cfg.resolution)
    cls_score, bbox_pred = box_head(feat, cfg)

    valid = layers.cast(
        layers.greater_equal(
            layers.cast(labels, "float32"),
            layers.fill_constant([1], "float32", 0.0),
        ),
        "float32",
    )
    cls_loss_all = layers.softmax_with_cross_entropy(
        cls_score, layers.relu(labels)
    )
    head_cls_loss = layers.reduce_sum(cls_loss_all * valid) / (
        layers.reduce_sum(valid) + 1.0
    )
    diff = (bbox_pred - bbox_targets) * bbox_iw
    head_reg_loss = layers.reduce_sum(
        layers.reduce_sum(layers.abs(diff), dim=1, keep_dim=True) * valid
    ) / (layers.reduce_sum(valid) + 1.0)

    # mask branch on the sampled roi set
    mask_rois, has_mask, mask_targets = det.generate_mask_labels(
        im_info, gt_classes, is_crowd, gt_segms, rois, labels,
        num_classes=cfg.class_num, resolution=cfg.resolution,
    )
    mfeat = _fpn_roi_extract(ps, mask_rois, cfg, cfg.resolution)
    mlogits = mask_head(mfeat, cfg)  # [R, C, 2M, 2M]
    mlogits = layers.pool2d(mlogits, pool_size=2, pool_stride=2,
                            pool_type="avg")  # back to [R, C, M, M]
    mlogits = layers.reshape(
        mlogits, [mlogits.shape[0], cfg.class_num * cfg.resolution ** 2]
    )
    mtgt = layers.cast(mask_targets, "float32")
    mvalid = layers.cast(
        layers.greater_equal(mtgt, layers.fill_constant([1], "float32", 0.0)),
        "float32",
    )
    mce = layers.sigmoid_cross_entropy_with_logits(mlogits, layers.relu(mtgt))
    mask_loss = layers.reduce_sum(mce * mvalid) / (
        layers.reduce_sum(mvalid) + 1.0
    )

    total = (rpn_cls_loss + rpn_reg_loss + head_cls_loss + head_reg_loss
             + mask_loss)
    return total, rpn_cls_loss, rpn_reg_loss, head_cls_loss, head_reg_loss, \
        mask_loss


# ---------------------------------------------------------------------------
# cross-image batched train path (r6)
# ---------------------------------------------------------------------------


def _per_image_mean(num, den):
    """mean_b(num_b / (den_b + 1)): the per-image-normalized loss
    reduction. num/den are [B]; matches the legacy single-image
    sum/(count+1) exactly at B=1."""
    return layers.reduce_mean(
        layers.elementwise_div(num, layers.scale(den, bias=1.0))
    )


def _rpn_losses_batched(rpn_outs, gt_boxes, is_crowd, im_info, cfg, B):
    """Batched RPN losses: anchors stay [A_tot, 4] (shared across images),
    scores/deltas carry [B, A_tot, ...], one batched target assignment
    emits per-image sampled indices gathered with take_along_axis."""
    all_scores, all_deltas, all_anchors = [], [], []
    for scores, deltas, anchors, _ in rpn_outs:
        s = layers.reshape(layers.transpose(scores, [0, 2, 3, 1]),
                           [B, -1, 1])
        d = layers.reshape(layers.transpose(deltas, [0, 2, 3, 1]),
                           [B, -1, 4])
        a = layers.reshape(anchors, [-1, 4])
        all_scores.append(s)
        all_deltas.append(d)
        all_anchors.append(a)
    scores = layers.concat(all_scores, axis=1)  # [B, A_tot, 1]
    deltas = layers.concat(all_deltas, axis=1)  # [B, A_tot, 4]
    anchors = layers.concat(all_anchors, axis=0)  # [A_tot, 4]

    loc_idx, score_idx, tgt_label, tgt_bbox, bbox_w = det.rpn_target_assign(
        anchors, gt_boxes, is_crowd=is_crowd, im_info=im_info,
        rpn_batch_size_per_im=cfg.batch_size_per_im,
    )  # [B, fg_cap] / [B, S] / [B, S, 1] / [B, fg_cap, 4] / [B, fg_cap, 4]
    S = score_idx.shape[1]
    samp_score = layers.take_along_axis(
        scores, layers.reshape(layers.relu(score_idx), [B, S, 1]), axis=1
    )  # [B, S, 1]
    label_f = layers.cast(tgt_label, "float32")
    valid = layers.cast(
        layers.greater_equal(
            label_f, layers.fill_constant([1], "float32", 0.0)
        ),
        "float32",
    )
    eps = 1e-6
    p = layers.clip(samp_score, eps, 1.0 - eps)
    ce = (0.0 - (label_f * layers.log(p)
                 + (1.0 - label_f) * layers.log(1.0 - p))) * valid
    cls_loss = _per_image_mean(
        layers.reduce_sum(ce, dim=[1, 2]),
        layers.reduce_sum(valid, dim=[1, 2]),
    )

    F = loc_idx.shape[1]
    samp_delta = layers.take_along_axis(
        deltas, layers.reshape(layers.relu(loc_idx), [B, F, 1]), axis=1
    )  # [B, F, 4]
    reg_valid = layers.cast(
        layers.greater_equal(
            layers.cast(loc_idx, "float32"),
            layers.fill_constant([1], "float32", 0.0),
        ),
        "float32",
    )  # [B, F]
    diff = (samp_delta - tgt_bbox) * bbox_w
    reg = layers.reduce_sum(layers.abs(diff), dim=[2])  # [B, F]
    reg_loss = _per_image_mean(
        layers.reduce_sum(reg * reg_valid, dim=[1]),
        layers.reduce_sum(reg_valid, dim=[1]),
    )
    return cls_loss, reg_loss


def _fpn_roi_extract_batched(ps, rois, cfg, resolution, B):
    """Batched FPN roi feature extraction: rois [B, R, 4] -> features
    [B*R, C, res, res] (B folded into the roi dim so the conv/fc heads
    run one wide op over every image's rois)."""
    multi_rois, restore, _nums = det.distribute_fpn_proposals(
        rois, cfg.min_level, cfg.max_level, 4, 224,
    )  # L x [B, R, 4], [B, R, 1]
    feats = []
    for lvl, (p, r) in enumerate(zip(ps, multi_rois)):
        f = det.roi_align(
            p, r, pooled_height=resolution, pooled_width=resolution,
            spatial_scale=1.0 / (2 ** (lvl + 2)), sampling_ratio=2,
        )  # [B, R, C, res, res]
        feats.append(f)
    stacked = layers.concat(feats, axis=1)  # [B, L*R, C, res, res]
    R = rois.shape[1]
    # restore[b, i] = row of roi i in image b's level-major concat (-1 for
    # dead rois -> clamps to row 0, masked by the losses downstream)
    idx = layers.reshape(layers.relu(restore), [B, R, 1, 1, 1])
    picked = layers.take_along_axis(stacked, idx, axis=1)
    C = stacked.shape[2]
    return layers.reshape(picked, [B * R, C, resolution, resolution])


def mask_rcnn_train_batched(images, gt_boxes, gt_classes, is_crowd,
                            gt_segms, im_info, cfg=None):
    """Cross-image batched train graph: ONE [B, ...] program for B images
    (the r6 re-architecture deleting the per-image unroll).

    images [B, 3, H, W]; gt_boxes [B, G, 4]; gt_classes/is_crowd [B, G];
    gt_segms [B, G, H, W]; im_info [B, 3]. Returns ``(losses, aux)``:
    losses = (total, rpn_cls, rpn_reg, head_cls, head_reg, mask) scalars
    (each per-image normalized then averaged over B) and aux =
    {"rois_num": [B] live-roi counts} for padding-waste observability
    (ops/detection_stats.record_roi_stats)."""
    cfg = cfg or MaskRCNNConfig()
    B = images.shape[0]
    cap = cfg.batch_size_per_im  # per-image RoI cap
    ps = resnet_fpn_backbone(images, cfg, is_test=False)
    rpn_outs = rpn_heads(ps, cfg)
    rpn_cls_loss, rpn_reg_loss = _rpn_losses_batched(
        rpn_outs, gt_boxes, is_crowd, im_info, cfg, B
    )

    # proposals per level -> collect (generate_proposals is natively
    # rank-lifted over the image batch)
    lvl_rois, lvl_scores, lvl_nums = [], [], []
    for scores, deltas, anchors, variances in rpn_outs:
        rois, probs, nums = det.generate_proposals(
            scores, deltas, im_info, anchors, variances,
            pre_nms_top_n=cfg.rpn_pre_nms, post_nms_top_n=cfg.rpn_post_nms,
            nms_thresh=0.7, min_size=0.0,
        )  # [B, post, 4] / [B, post, 1] / [B]
        lvl_rois.append(rois)
        lvl_scores.append(probs)
        lvl_nums.append(nums)
    rois, _collect_num = det.collect_fpn_proposals(
        lvl_rois, lvl_scores, cfg.min_level, cfg.max_level,
        post_nms_top_n=cfg.rpn_post_nms, rois_nums=lvl_nums,
    )  # [B, post, 4]

    (rois, labels, bbox_targets, bbox_iw, _bbox_ow, rois_num,
     _ov) = det.generate_proposal_labels(
        rois, gt_classes, is_crowd, gt_boxes, im_info,
        batch_size_per_im=cap, class_nums=cfg.class_num,
        rois_num=_collect_num,
    )  # [B, cap, 4] / [B, cap, 1] / [B, cap, 4C] / ... / [B]

    feat = _fpn_roi_extract_batched(ps, rois, cfg, cfg.resolution, B)
    cls_score, bbox_pred = box_head(feat, cfg)  # [B*cap, C] / [B*cap, 4C]

    labels_flat = layers.reshape(labels, [B * cap, 1])
    valid = layers.cast(
        layers.greater_equal(
            layers.cast(labels_flat, "float32"),
            layers.fill_constant([1], "float32", 0.0),
        ),
        "float32",
    )  # [B*cap, 1]
    valid_im = layers.reshape(valid, [B, cap])
    cls_loss_all = layers.softmax_with_cross_entropy(
        cls_score, layers.relu(labels_flat)
    )  # [B*cap, 1]
    head_cls_loss = _per_image_mean(
        layers.reduce_sum(
            layers.reshape(cls_loss_all, [B, cap]) * valid_im, dim=[1]
        ),
        layers.reduce_sum(valid_im, dim=[1]),
    )
    diff = (bbox_pred - layers.reshape(bbox_targets, [B * cap, -1])) \
        * layers.reshape(bbox_iw, [B * cap, -1])
    reg_rows = layers.reduce_sum(layers.abs(diff), dim=[1], keep_dim=True)
    head_reg_loss = _per_image_mean(
        layers.reduce_sum(
            layers.reshape(reg_rows, [B, cap]) * valid_im, dim=[1]
        ),
        layers.reduce_sum(valid_im, dim=[1]),
    )

    # mask branch on the sampled roi set
    mask_rois, _has_mask, mask_targets = det.generate_mask_labels(
        im_info, gt_classes, is_crowd, gt_segms, rois, labels,
        num_classes=cfg.class_num, resolution=cfg.resolution,
    )  # [B, cap, 4] / [B, cap, 1] / [B, cap, C*M^2]
    mfeat = _fpn_roi_extract_batched(ps, mask_rois, cfg, cfg.resolution, B)
    mlogits = mask_head(mfeat, cfg)  # [B*cap, C, 2M, 2M]
    mlogits = layers.pool2d(mlogits, pool_size=2, pool_stride=2,
                            pool_type="avg")  # back to [B*cap, C, M, M]
    mlogits = layers.reshape(
        mlogits, [B * cap, cfg.class_num * cfg.resolution ** 2]
    )
    mtgt = layers.cast(
        layers.reshape(mask_targets, [B * cap, -1]), "float32"
    )
    mvalid = layers.cast(
        layers.greater_equal(mtgt, layers.fill_constant([1], "float32", 0.0)),
        "float32",
    )
    mce = layers.sigmoid_cross_entropy_with_logits(mlogits, layers.relu(mtgt))
    K = cfg.class_num * cfg.resolution ** 2
    mask_loss = _per_image_mean(
        layers.reduce_sum(
            layers.reshape(mce * mvalid, [B, cap * K]), dim=[1]
        ),
        layers.reduce_sum(
            layers.reshape(mvalid, [B, cap * K]), dim=[1]
        ),
    )

    total = (rpn_cls_loss + rpn_reg_loss + head_cls_loss + head_reg_loss
             + mask_loss)
    losses = (total, rpn_cls_loss, rpn_reg_loss, head_cls_loss,
              head_reg_loss, mask_loss)
    return losses, {"rois_num": rois_num}


def mask_rcnn_infer(image, im_info, cfg=None):
    """Proposal -> box head -> NMS; returns detections [K, 6] and the
    per-detection mask logits."""
    cfg = cfg or MaskRCNNConfig()
    ps = resnet_fpn_backbone(image, cfg, is_test=True)
    rpn_outs = rpn_heads(ps, cfg)
    lvl_rois, lvl_scores, lvl_nums = [], [], []
    for scores, deltas, anchors, variances in rpn_outs:
        rois, probs, nums = det.generate_proposals(
            scores, deltas, im_info, anchors, variances,
            pre_nms_top_n=cfg.rpn_pre_nms, post_nms_top_n=cfg.rpn_post_nms,
            nms_thresh=0.7, min_size=0.0,
        )
        lvl_rois.append(layers.reshape(rois, [-1, 4]))
        lvl_scores.append(layers.reshape(probs, [-1, 1]))
        lvl_nums.append(nums)
    rois, _ = det.collect_fpn_proposals(
        lvl_rois, lvl_scores, cfg.min_level, cfg.max_level,
        post_nms_top_n=cfg.rpn_post_nms, rois_nums=lvl_nums,
    )
    feat = _fpn_roi_extract(ps, rois, cfg, cfg.resolution)
    cls_score, bbox_pred = box_head(feat, cfg)
    probs = layers.softmax(cls_score)  # [R, C]
    # decode per-class boxes against the rois (reference inference path:
    # box_coder decode with the training bbox_reg_weights as variance,
    # inverting generate_proposal_labels' encoding) + NMS
    var4 = layers.assign_value([0.1, 0.1, 0.2, 0.2])
    decoded, assign = det.box_decoder_and_assign(
        rois, var4, bbox_pred, probs,
    )
    R = rois.shape[0]
    # each roi contributes its best-class box (OutputAssignBox); NMS over
    # the class-score matrix picks labels
    shared = layers.reshape(assign, [1, R, 4])
    scores_t = layers.transpose(layers.reshape(probs, [1, R, -1]), [0, 2, 1])
    out, _nums = det.multiclass_nms(shared, scores_t, score_threshold=0.05,
                                    nms_top_k=cfg.rpn_post_nms,
                                    keep_top_k=100, nms_threshold=0.5,
                                    background_label=0)
    # mask head runs on the KEPT detections (reference order: NMS first,
    # then the mask branch on the final boxes), so mask row i IS detection i
    det_boxes = layers.reshape(
        layers.slice(out, axes=[2], starts=[2], ends=[6]), [-1, 4]
    )
    mfeat = _fpn_roi_extract(ps, det_boxes, cfg, cfg.resolution)
    mlogits = mask_head(mfeat, cfg)
    return out, mlogits
