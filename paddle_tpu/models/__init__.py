"""Model zoo: static-graph builders for the reference's benchmark models
(BASELINE.md: ResNet-50 ImageNet, BERT-base, plus small book-test models).

Each builder appends ops into the current default program (fluid style) and
returns the variables a training loop needs. Models are written against the
public layers API only — they double as end-to-end tests of the framework
(the reference's tests/book/ strategy, SURVEY.md §4.3).
"""

from .resnet import resnet  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    bert_encoder,
    bert_pretrain,
    bert_tp_shardings,
)
from .mask_rcnn import (  # noqa: F401
    MaskRCNNConfig,
    mask_rcnn_infer,
    mask_rcnn_train,
)
from .deepfm import DeepFMConfig, deepfm  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    gpt_decoder,
    gpt_lm_loss,
    gpt_tp_shardings,
)
from .zoo import MODEL_BUILDERS, BuiltModel, build_model  # noqa: F401
from .yolov3 import (  # noqa: F401
    YoloConfig,
    darknet53,
    yolov3_heads,
    yolov3_infer,
    yolov3_train,
)
