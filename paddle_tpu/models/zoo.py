"""One-call builders for every bundled model, sized for CI.

Shared by ``tools/program_lint.py`` (build every model, run the static
verifier over it) and the clean-bill tests in
``tests/test_program_analysis.py``. Each builder constructs a FRESH
(main, startup) pair with an optimizer applied — the trained program is
what the verifier must pass, since backward + optimizer rewrites are where
declaration/emitter drift historically hides — and returns a
:class:`BuiltModel` naming the feeds and fetches the dataflow analyses
key on.

Builders only *build* graphs (no Executor.run), so the zoo stays cheap
enough for a lint stage: a few seconds per model on CPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BuiltModel:
    name: str
    main: object
    startup: object
    feed_names: tuple
    fetch_names: tuple
    # mesh axes this model is meant to shard over, when linting the
    # collective schedule: {axis: size}; None = single-chip program
    mesh_axes: dict | None = None
    spmd_mode: str = "shard_map"
    manual_axes: tuple = ()
    shardings: dict = field(default_factory=dict)


def _fresh(seed=7):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    return main, startup


def build_resnet():
    import paddle_tpu as fluid
    from .resnet import resnet_train_net

    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        img = fluid.data("image", [4, 3, 32, 32], "float32")
        label = fluid.data("label", [4, 1], "int64")
        loss, acc = resnet_train_net(img, label, depth=18, class_num=10)
        fluid.optimizer.SGD(0.01).minimize(loss, startup)
    return BuiltModel(
        "resnet", main, startup, ("image", "label"),
        (loss.name, acc.name),
    )


def build_bert():
    import paddle_tpu as fluid
    from .bert import BertConfig, bert_pretrain

    cfg = BertConfig.tiny()
    b, s = 2, 16
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    return BuiltModel(
        "bert", main, startup, ("ids", "types", "mask", "labels"),
        (loss.name,),
    )


def build_gpt():
    import paddle_tpu as fluid
    from .gpt import GPTConfig, gpt_lm_loss

    cfg = GPTConfig.tiny()
    b, s = 2, 16
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [b, s], "int64")
        loss = gpt_lm_loss(ids, cfg)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    return BuiltModel("gpt", main, startup, ("ids",), (loss.name,))


def build_yolov3():
    import paddle_tpu as fluid
    from .yolov3 import YoloConfig, yolov3_train

    cfg = YoloConfig.tiny(class_num=3)
    n, s, b = 2, 64, 4
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [n, 3, s, s])
        gt = fluid.data("gt", [n, b, 4])
        lab = fluid.data("lab", [n, b], "int64")
        loss = yolov3_train(img, gt, lab, cfg)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    return BuiltModel(
        "yolov3", main, startup, ("img", "gt", "lab"), (loss.name,)
    )


def build_deepfm():
    import paddle_tpu as fluid
    from .deepfm import DeepFMConfig, deepfm

    cfg = DeepFMConfig(
        vocab_size=512, num_fields=6, embed_dim=8, mlp_sizes=(16,)
    )
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        ids = fluid.data("feat_ids", [8, cfg.num_fields], "int64")
        label = fluid.data("label", [8, 1], "float32")
        loss, predict = deepfm(ids, label, cfg)
        fluid.optimizer.Adam(1e-2).minimize(loss, startup)
    return BuiltModel(
        "deepfm", main, startup, ("feat_ids", "label"),
        (loss.name, predict.name),
    )


def build_deepfm_fused():
    """The PR-11 embedding-engine layout: per-slot lookups (the reference
    CTR shape, 2F gather sites) coalesced by ``embedding.fuse_lookups``
    into one ``fused_lookup_table`` per table width, with the tables
    row-sharded over the "ps" axis — the graph the fused bench leg and the
    serving recommendation mix dispatch."""
    import paddle_tpu as fluid
    from ..embedding import fuse_lookups
    from ..parallel.sparse import shard_sparse_tables
    from .deepfm import DeepFMConfig, deepfm

    cfg = DeepFMConfig(
        vocab_size=512, num_fields=6, embed_dim=8, mlp_sizes=(16,)
    )
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        ids = fluid.data("feat_ids", [8, cfg.num_fields], "int64")
        label = fluid.data("label", [8, 1], "float32")
        loss, predict = deepfm(ids, label, cfg, per_slot=True)
        fuse_lookups(main)
        fluid.optimizer.Adam(1e-2).minimize(loss, startup)
        shard_sparse_tables(main)
    return BuiltModel(
        "deepfm_fused", main, startup, ("feat_ids", "label"),
        (loss.name, predict.name),
        mesh_axes={"ps": 8},
    )


def build_mask_rcnn():
    import paddle_tpu as fluid
    from . import mask_rcnn

    cfg = mask_rcnn.MaskRCNNConfig.tiny()
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        image = fluid.data("image", [1, 3, 64, 64])
        gt_boxes = fluid.data("gt_boxes", [2, 4])
        gt_classes = fluid.data("gt_classes", [2], dtype="int32")
        is_crowd = fluid.data("is_crowd", [2], dtype="int32")
        gt_segms = fluid.data("gt_segms", [2, 64, 64])
        im_info = fluid.data("im_info", [1, 3])
        losses = mask_rcnn.mask_rcnn_train(
            image, gt_boxes, gt_classes, is_crowd, gt_segms, im_info, cfg
        )
        fluid.optimizer.SGD(0.01).minimize(losses[0])
    return BuiltModel(
        "mask_rcnn", main, startup,
        ("image", "gt_boxes", "gt_classes", "is_crowd", "gt_segms",
         "im_info"),
        tuple(v.name for v in losses),
    )


def build_mask_rcnn_batched():
    """The r6 cross-image batched Mask R-CNN graph (ONE [B, ...] program
    for B images instead of B unrolled one-image graphs) — the shape the
    bench leg trains; linting it keeps the batched detection-op
    `infer_shapes` signatures under the PR-5 shape replay."""
    import paddle_tpu as fluid
    from . import mask_rcnn

    cfg = mask_rcnn.MaskRCNNConfig.tiny()
    B, size, G = 2, 64, 2
    main, startup = _fresh()
    with fluid.program_guard(main, startup):
        images = fluid.data("images", [B, 3, size, size])
        gt_boxes = fluid.data("gt_boxes", [B, G, 4])
        gt_classes = fluid.data("gt_classes", [B, G], dtype="int32")
        is_crowd = fluid.data("is_crowd", [B, G], dtype="int32")
        gt_segms = fluid.data("gt_segms", [B, G, size, size])
        im_info = fluid.data("im_info", [B, 3])
        losses, aux = mask_rcnn.mask_rcnn_train_batched(
            images, gt_boxes, gt_classes, is_crowd, gt_segms, im_info, cfg
        )
        fluid.optimizer.SGD(0.01).minimize(losses[0])
    return BuiltModel(
        "mask_rcnn_batched", main, startup,
        ("images", "gt_boxes", "gt_classes", "is_crowd", "gt_segms",
         "im_info"),
        tuple(v.name for v in losses) + (aux["rois_num"].name,),
    )


def build_bert_3d():
    from .bert import BertConfig
    from .bert_3d import bert_3d_shardings, build_bert_3d

    cfg = BertConfig.tiny()
    num_stages = 2
    main, startup, loss = build_bert_3d(
        cfg, batch=4, seq_len=16, num_stages=num_stages, microbatches=2,
        dp=2, pipeline_mode="uniform",
    )
    return BuiltModel(
        "bert_3d", main, startup, ("ids", "types", "mask", "labels"),
        (loss.name,),
        mesh_axes={"dp": 2, "mp": 2, "pp": num_stages},
        spmd_mode="hybrid",
        manual_axes=("dp", "pp"),
        shardings=bert_3d_shardings(cfg, num_stages),
    )


MODEL_BUILDERS = {
    "resnet": build_resnet,
    "bert": build_bert,
    "gpt": build_gpt,
    "yolov3": build_yolov3,
    "deepfm": build_deepfm,
    "deepfm_fused": build_deepfm_fused,
    "mask_rcnn": build_mask_rcnn,
    "mask_rcnn_batched": build_mask_rcnn_batched,
    "bert_3d": build_bert_3d,
}


def build_model(name, with_mesh=True):
    """Build one bundled model; attach its mesh (when it declares axes and
    enough devices exist) so the collective-schedule lint has bound axes
    to check. Returns the BuiltModel with ``main._mesh`` set or not."""
    bm = MODEL_BUILDERS[name]()
    if with_mesh and bm.mesh_axes:
        import numpy as np

        import jax

        need = int(np.prod(list(bm.mesh_axes.values())))
        if len(jax.devices()) >= need:
            from ..parallel import make_mesh, shard_program

            mesh = make_mesh(
                dict(bm.mesh_axes), jax.devices()[:need]
            )
            shard_program(
                bm.main, mesh, bm.shardings or None, mode=bm.spmd_mode,
                manual_axes=bm.manual_axes or None,
            )
    return bm
