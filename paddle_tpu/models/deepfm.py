"""DeepFM CTR model (the sparse/PS workload from BASELINE.md).

Reference workload shape: huge sparse id features -> first-order weights +
FM second-order factor interactions + a deep MLP tower, trained with
row-sharded embedding tables (the reference used pserver-resident tables,
distributed_lookup_table_op.cc; here tables shard over the "ps" mesh axis,
ops/sparse.py).
"""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


class DeepFMConfig:
    def __init__(self, vocab_size=100000, num_fields=10, embed_dim=16,
                 mlp_sizes=(64, 32)):
        self.vocab_size = vocab_size
        self.num_fields = num_fields
        self.embed_dim = embed_dim
        self.mlp_sizes = tuple(mlp_sizes)


def deepfm(feat_ids, label, cfg, axis="ps"):
    """feat_ids: [B, F] int64 global feature ids; label: [B, 1] float32.
    Returns (avg_logloss, predict)."""
    b, f = feat_ids.shape

    # first-order: sharded [V, 1] table
    w1 = layers.sparse_embedding(
        feat_ids, [cfg.vocab_size, 1],
        param_attr=ParamAttr(name="deepfm_w1"), axis=axis,
    )  # [B, F, 1]
    first = layers.reduce_sum(layers.reshape(w1, [b, f]), 1, keep_dim=True)

    # factor embeddings: sharded [V, D] table
    emb = layers.sparse_embedding(
        feat_ids, [cfg.vocab_size, cfg.embed_dim],
        param_attr=ParamAttr(name="deepfm_emb"), axis=axis,
    )  # [B, F, D]

    # FM second order: 0.5 * sum_d((sum_f v)^2 - sum_f v^2)
    sum_f = layers.reduce_sum(emb, 1)  # [B, D]
    sum_sq = layers.square(sum_f)
    sq_sum = layers.reduce_sum(layers.square(emb), 1)
    fm = layers.scale(
        layers.reduce_sum(sum_sq - sq_sum, 1, keep_dim=True), scale=0.5
    )

    # deep tower
    deep = layers.reshape(emb, [b, f * cfg.embed_dim])
    for i, sz in enumerate(cfg.mlp_sizes):
        deep = layers.fc(
            deep, sz, act="relu",
            param_attr=ParamAttr(name=f"deepfm_mlp{i}_w"),
            bias_attr=ParamAttr(name=f"deepfm_mlp{i}_b"),
        )
    deep = layers.fc(
        deep, 1,
        param_attr=ParamAttr(name="deepfm_out_w"),
        bias_attr=ParamAttr(name="deepfm_out_b"),
    )

    logit = first + fm + deep
    predict = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return loss, predict
