"""DeepFM CTR model (the sparse/PS workload from BASELINE.md).

Reference workload shape: huge sparse id features -> first-order weights +
FM second-order factor interactions + a deep MLP tower, trained with
row-sharded embedding tables (the reference used pserver-resident tables,
distributed_lookup_table_op.cc; here tables shard over the "ps" mesh axis,
ops/sparse.py).
"""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


class DeepFMConfig:
    def __init__(self, vocab_size=100000, num_fields=10, embed_dim=16,
                 mlp_sizes=(64, 32), dense_dim=0):
        self.vocab_size = vocab_size
        self.num_fields = num_fields
        self.embed_dim = embed_dim
        self.mlp_sizes = tuple(mlp_sizes)
        # continuous features fed to BOTH the wide (linear) half and the
        # deep tower (the Criteo layout: 26 sparse + 13 dense)
        self.dense_dim = dense_dim

    @classmethod
    def criteo(cls):
        """The reference CTR benchmark shape (PaddleRec DeepFM on Criteo:
        26 sparse fields over a ~1M id space + 13 dense, d=10 factors,
        400x400x400 tower)."""
        return cls(vocab_size=1000000, num_fields=26, embed_dim=10,
                   mlp_sizes=(400, 400, 400), dense_dim=13)


def deepfm(feat_ids, label, cfg, axis="ps", dense_input=None,
           per_slot=False):
    """feat_ids: [B, F] int64 global feature ids; label: [B, 1] float32;
    dense_input: optional [B, dense_dim] float32 continuous features.
    Returns (avg_logloss, predict).

    The wide half is the FM itself — first-order sparse weights plus the
    factorized second-order term, which IS all pairwise feature crosses
    (sum_{i<j} <v_i, v_j> x_i x_j) without materializing the cross matrix;
    dense features get a linear wide term and join the deep tower input.

    ``per_slot=True`` builds the reference CTR layout (PaddleRec DeepFM:
    one embedding gather PER SPARSE SLOT against the shared global-id
    tables) — 2F+ lookup dispatch sites instead of 2, which is exactly the
    shape ``embedding.fuse_lookups`` coalesces back into one
    ``fused_lookup_table`` per table width. Numerically identical to the
    default layout."""
    b, f = feat_ids.shape

    if per_slot:
        # gather phase first (2F lookup sites, nothing reads them yet —
        # the layout fuse_lookups coalesces into one op per table width),
        # assembly phase after
        w1_raw, emb_raw = [], []
        for i in range(f):
            slot_ids = layers.slice(feat_ids, [1], [i], [i + 1])  # [B, 1]
            w1_raw.append(layers.sparse_embedding(
                slot_ids, [cfg.vocab_size, 1],
                param_attr=ParamAttr(name="deepfm_w1"), axis=axis,
            ))  # [B, 1]
            emb_raw.append(layers.sparse_embedding(
                slot_ids, [cfg.vocab_size, cfg.embed_dim],
                param_attr=ParamAttr(name="deepfm_emb"), axis=axis,
            ))  # [B, D]
        w1 = layers.concat(
            [layers.reshape(v, [b, 1, 1]) for v in w1_raw], axis=1
        )  # [B, F, 1]
        emb = layers.concat(
            [layers.reshape(v, [b, 1, cfg.embed_dim]) for v in emb_raw],
            axis=1,
        )  # [B, F, D]
    else:
        # first-order: sharded [V, 1] table
        w1 = layers.sparse_embedding(
            feat_ids, [cfg.vocab_size, 1],
            param_attr=ParamAttr(name="deepfm_w1"), axis=axis,
        )  # [B, F, 1]
        # factor embeddings: sharded [V, D] table
        emb = layers.sparse_embedding(
            feat_ids, [cfg.vocab_size, cfg.embed_dim],
            param_attr=ParamAttr(name="deepfm_emb"), axis=axis,
        )  # [B, F, D]
    first = layers.reduce_sum(layers.reshape(w1, [b, f]), 1, keep_dim=True)

    # FM second order: 0.5 * sum_d((sum_f v)^2 - sum_f v^2)
    sum_f = layers.reduce_sum(emb, 1)  # [B, D]
    sum_sq = layers.square(sum_f)
    sq_sum = layers.reduce_sum(layers.square(emb), 1)
    fm = layers.scale(
        layers.reduce_sum(sum_sq - sq_sum, 1, keep_dim=True), scale=0.5
    )

    # dense wide term (linear) + deep-tower concat
    wide_dense = None
    deep = layers.reshape(emb, [b, f * cfg.embed_dim])
    if dense_input is not None:
        wide_dense = layers.fc(
            dense_input, 1,
            param_attr=ParamAttr(name="deepfm_wide_w"),
            bias_attr=ParamAttr(name="deepfm_wide_b"),
        )
        deep = layers.concat([deep, dense_input], axis=1)
    for i, sz in enumerate(cfg.mlp_sizes):
        deep = layers.fc(
            deep, sz, act="relu",
            param_attr=ParamAttr(name=f"deepfm_mlp{i}_w"),
            bias_attr=ParamAttr(name=f"deepfm_mlp{i}_b"),
        )
    deep = layers.fc(
        deep, 1,
        param_attr=ParamAttr(name="deepfm_out_w"),
        bias_attr=ParamAttr(name="deepfm_out_b"),
    )

    logit = first + fm + deep
    if wide_dense is not None:
        logit = logit + wide_dense
    predict = layers.sigmoid(logit)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return loss, predict
