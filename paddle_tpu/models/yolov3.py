"""YOLOv3 — DarkNet-53 backbone + 3-scale FPN heads (the reference's
detection flagship; ops behavior from operators/detection/yolov3_loss_op.h
and yolo_box_op.cc; model topology per the YOLOv3 paper, built on the
public layers API only — like models/resnet.py, convs run NCHW at the op
boundary and NHWC inside, kernels on the MXU via XLA).

`yolov3_train` returns the summed three-scale loss; `yolov3_infer` decodes
all heads with yolo_box and fuses them through multiclass_nms. A `scale`
knob shrinks every channel count for tests/dry-runs (scale=1 is the paper
model: 53-conv backbone, 75-channel heads for COCO).
"""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr

# paper anchors (COCO, 416 input); mask [6,7,8] = coarsest stride-32 head
DEFAULT_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
                   59, 119, 116, 90, 156, 198, 373, 326]
DEFAULT_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


class YoloConfig:
    def __init__(self, class_num=80, anchors=None, anchor_masks=None,
                 scale=1.0, ignore_thresh=0.7, use_label_smooth=True):
        self.class_num = class_num
        self.anchors = list(anchors or DEFAULT_ANCHORS)
        self.anchor_masks = [list(m) for m in
                             (anchor_masks or DEFAULT_ANCHOR_MASKS)]
        if not 1 <= len(self.anchor_masks) <= 3:
            raise ValueError("anchor_masks: 1-3 scales supported "
                             "(heads start at stride 32 and halve)")
        self.scale = float(scale)
        self.ignore_thresh = ignore_thresh
        self.use_label_smooth = use_label_smooth

    def ch(self, n):
        return max(4, int(n * self.scale))

    @classmethod
    def tiny(cls, class_num=4):
        """1/8-width model for CPU tests and dry runs."""
        return cls(class_num=class_num, scale=0.125)


def _cbl(x, ch, k, stride, prefix):
    """conv-bn-leaky_relu, the darknet unit."""
    x = layers.conv2d(
        x, ch, k, stride=stride, padding=(k - 1) // 2, bias_attr=False,
        param_attr=ParamAttr(name=f"{prefix}_w"),
    )
    return layers.batch_norm(
        x, act="leaky_relu",
        param_attr=ParamAttr(name=f"{prefix}_bn_s"),
        bias_attr=ParamAttr(name=f"{prefix}_bn_b"),
        moving_mean_name=f"{prefix}_bn_m",
        moving_variance_name=f"{prefix}_bn_v",
    )


def _res_block(x, ch, prefix):
    """1x1 bottleneck + 3x3, residual add (darknet53 block)."""
    s = _cbl(x, ch // 2, 1, 1, f"{prefix}_a")
    s = _cbl(s, ch, 3, 1, f"{prefix}_b")
    return x + s


def darknet53(img, cfg, prefix="dark"):
    """Backbone; returns the C3/C4/C5 feature maps (strides 8/16/32)."""
    depths = (1, 2, 8, 8, 4)
    x = _cbl(img, cfg.ch(32), 3, 1, f"{prefix}_stem")
    feats = []
    ch = 32
    for stage, blocks in enumerate(depths):
        ch *= 2
        x = _cbl(x, cfg.ch(ch), 3, 2, f"{prefix}_down{stage}")
        for b in range(blocks):
            x = _res_block(x, cfg.ch(ch), f"{prefix}_s{stage}b{b}")
        if stage >= 2:
            feats.append(x)
    return feats  # [C3 (stride 8), C4 (16), C5 (32)]


def _detection_block(x, ch, prefix):
    """5-conv block; returns (route for the next scale, head input)."""
    for i in range(2):
        x = _cbl(x, ch, 1, 1, f"{prefix}_r{i}a")
        x = _cbl(x, ch * 2, 3, 1, f"{prefix}_r{i}b")
    route = _cbl(x, ch, 1, 1, f"{prefix}_route")
    tip = _cbl(route, ch * 2, 3, 1, f"{prefix}_tip")
    return route, tip


def yolov3_heads(img, cfg, prefix="yolo"):
    """Backbone + FPN neck; returns raw head outputs
    [stride 32, stride 16, stride 8], each [N, M*(5+C), H, W]."""
    c3, c4, c5 = darknet53(img, cfg, prefix=f"{prefix}_dark")
    outputs = []
    route = None
    scales = [c5, c4, c3][: len(cfg.anchor_masks)]
    for i, feat in enumerate(scales):
        if route is not None:
            route = _cbl(route, cfg.ch(256 // (2 ** (i - 1))), 1, 1,
                         f"{prefix}_lat{i}")
            route = layers.resize_nearest(route, scale=2.0)
            feat = layers.concat([route, feat], axis=1)
        route, tip = _detection_block(
            feat, cfg.ch(512 // (2 ** i)), f"{prefix}_det{i}"
        )
        n_out = len(cfg.anchor_masks[i]) * (5 + cfg.class_num)
        outputs.append(
            layers.conv2d(
                tip, n_out, 1,
                param_attr=ParamAttr(name=f"{prefix}_head{i}_w"),
                bias_attr=ParamAttr(name=f"{prefix}_head{i}_b"),
            )
        )
    return outputs


def yolov3_train(img, gt_box, gt_label, cfg, gt_score=None, prefix="yolo"):
    """Mean over the batch of the three-scale yolov3_loss sum."""
    heads = yolov3_heads(img, cfg, prefix=prefix)
    losses = []
    for i, head in enumerate(heads):
        per_image = layers.yolov3_loss(
            head, gt_box, gt_label,
            anchors=cfg.anchors,
            anchor_mask=cfg.anchor_masks[i],
            class_num=cfg.class_num,
            ignore_thresh=cfg.ignore_thresh,
            downsample_ratio=32 // (2 ** i),
            gt_score=gt_score,
            use_label_smooth=cfg.use_label_smooth,
        )
        losses.append(layers.reduce_mean(per_image))
    total = losses[0]
    for extra in losses[1:]:
        total = total + extra
    return total


def yolov3_infer(img, img_size, cfg, prefix="yolo",
                 conf_thresh=0.01, nms_thresh=0.45, keep_top_k=100):
    """Decode + NMS: returns ([N, keep_top_k, 6] label/score/x0y0x1y1,
    valid counts [N])."""
    heads = yolov3_heads(img, cfg, prefix=prefix)
    boxes, scores = [], []
    for i, head in enumerate(heads):
        masked_anchors = []
        for a in cfg.anchor_masks[i]:
            masked_anchors += cfg.anchors[2 * a:2 * a + 2]
        b, s = layers.yolo_box(
            head, img_size, anchors=masked_anchors,
            class_num=cfg.class_num, conf_thresh=conf_thresh,
            downsample_ratio=32 // (2 ** i),
        )
        boxes.append(b)
        scores.append(layers.transpose(s, [0, 2, 1]))
    return layers.multiclass_nms(
        layers.concat(boxes, axis=1),
        layers.concat(scores, axis=2),
        score_threshold=conf_thresh,
        nms_threshold=nms_thresh,
        keep_top_k=keep_top_k,
    )
