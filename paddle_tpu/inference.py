"""Inference predictor API (reference paddle/fluid/inference/:
AnalysisConfig paddle_analysis_config.h, AnalysisPredictor
analysis_predictor.cc, create_paddle_predictor, PaddleTensor,
ZeroCopyTensor inference/api/details/zero_copy_tensor.cc).

TPU-native: load_inference_model gives the pruned Program; the predictor
compiles it once per input-shape set through the ordinary Executor (whole
block -> one XLA executable — the role of the reference's IR pass manager +
NaiveExecutor + TensorRT engines collapses into XLA). The config knobs
ACT (r4, VERDICT r3 item 5):

  * enable_bf16()            — AMP-rewrites the inference program so the
                               matmul/conv path runs the MXU in bf16 (the
                               reference's enable_mkldnn_bfloat16 /
                               TRT-fp16 analogue).
  * set_optim_cache_dir(d)   — persistent XLA compilation cache on disk
                               (reference SetOptimCacheDir): later
                               processes reuse compiles.
  * set_batch_buckets([...]) — pad run batches up to fixed bucket sizes so
                               arbitrary batch sizes reuse a handful of
                               executables instead of compiling each.
  * save/load_executable     — explicit AOT serialization of the compiled
                               step (Executor.serialize_executable): a
                               deployment process starts serving with NO
                               XLA compilation (the TRT engine-cache
                               analogue).

Zero-copy: Predictor.run_zero_copy feeds caller-owned buffers without a
host-side staging copy (np.frombuffer view) and returns device-backed
outputs materialized once into arrays whose buffers the caller may read
in place (the C API points PD_TensorC.data straight at them)."""

from __future__ import annotations

import os

import numpy as np

from .errors import InvalidArgumentError, PreconditionNotMetError

# the XLA compilation cache dir applied by any predictor in this process
# (jax.config is process-global); conflicting dirs raise at construction
_applied_optim_cache_dir = None


class AnalysisConfig:
    def __init__(self, model_dir=None, params_file=None, model_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self.model_file = model_file
        self._use_feed_fetch_ops = False
        self._switch_ir_optim = True  # accepted; XLA owns optimization
        self._bf16 = False
        self._batch_buckets = None
        self._optim_cache_dir = None
        self._aot_path = None

    # -- knobs that act -------------------------------------------------
    def enable_bf16(self):
        """Run the white-list op set (matmuls/convs) in bfloat16 — the
        reference's low-precision inference switch
        (enable_mkldnn_bfloat16, paddle_analysis_config.h)."""
        self._bf16 = True

    def set_optim_cache_dir(self, path):
        """Persist XLA compilations under `path` (reference
        SetOptimCacheDir): the first process pays the compile, later ones
        load from disk.

        PROCESS-GLOBAL: the XLA compilation cache is a jax.config knob, so
        every compile in the process (other predictors, training code)
        shares the directory and the zeroed persistence thresholds once any
        predictor with this knob is constructed. Two predictors configuring
        DIFFERENT dirs is an error (raised at construction) — the cache
        cannot be scoped per-predictor."""
        self._optim_cache_dir = str(path)

    def set_batch_buckets(self, sizes):
        """Pad run() batches up to the nearest of `sizes` so arbitrary
        batch sizes share executables (one compile per bucket, not per
        batch size). Contract: all feeds share the LEADING batch axis, and
        fetches must be per-sample tensors with the batch leading too —
        un-padding slices axis 0 of batch-sized outputs. A fetch that
        REDUCES over the batch (a mean loss, say) would silently include
        the zero padding rows; keep such reductions out of bucketed
        predictors."""
        sizes = sorted(int(s) for s in sizes)
        if not sizes or sizes[0] <= 0:
            raise InvalidArgumentError(
                f"batch buckets must be positive, got {sizes}"
            )
        self._batch_buckets = sizes

    def set_aot_executable_path(self, path):
        """Load a serialized executable (Predictor.save_executable) at
        construction — serving starts with no XLA compilation."""
        self._aot_path = str(path)

    # -- parity shims (inherently device-moot on TPU) -------------------
    def disable_glog_info(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = flag

    def switch_use_feed_fetch_ops(self, flag):
        self._use_feed_fetch_ops = flag

    def enable_use_gpu(self, *a, **k):  # API parity: device is the TPU
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        # XLA buffer assignment already minimizes/reuses buffers
        pass


class PaddleTensor:
    """Host-side input/output tensor (reference paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name=""):
        self.name = name
        self.data = np.asarray(data) if data is not None else None

    def as_ndarray(self):
        return np.asarray(self.data)


class Predictor:
    """AnalysisPredictor parity: load once, run many."""

    def __init__(self, config):
        from . import io as _io
        from .framework.executor import Executor
        from .framework.scope import Scope, scope_guard

        if config.model_dir is None:
            raise InvalidArgumentError(
                "AnalysisConfig.model_dir is required"
            )
        self._config = config
        if config._optim_cache_dir:
            import jax

            global _applied_optim_cache_dir
            new_dir = os.path.abspath(config._optim_cache_dir)
            if (_applied_optim_cache_dir is not None
                    and _applied_optim_cache_dir != new_dir):
                raise PreconditionNotMetError(
                    "set_optim_cache_dir is process-global (XLA compilation "
                    f"cache): already configured to "
                    f"{_applied_optim_cache_dir!r}, cannot switch to "
                    f"{new_dir!r} in the same process"
                )
            os.makedirs(new_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", new_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            _applied_optim_cache_dir = new_dir
        self._scope = Scope()
        self._exe = Executor()
        with scope_guard(self._scope):
            (
                self._program,
                self._feed_names,
                self._fetch_vars,
            ) = _io.load_inference_model(
                config.model_dir,
                self._exe,
                model_filename=getattr(config, "model_file", None),
                params_filename=getattr(config, "params_file", None),
            )
        if config._bf16:
            from .contrib.mixed_precision import (AutoMixedPrecisionLists,
                                                  fp16_utils)

            fp16_utils.rewrite_program(
                self._program, AutoMixedPrecisionLists(),
                dest_dtype="bfloat16",
            )
        self._last_outputs = None  # keepalive for zero-copy readers
        self._aot_feed_sig = None
        if config._aot_path:
            self._load_executable_meta(config._aot_path)

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [
            v if isinstance(v, str) else v.name for v in self._fetch_vars
        ]

    # -- AOT ------------------------------------------------------------
    def save_executable(self, path, sample_inputs):
        """Compile for `sample_inputs` (list in feed order) and serialize
        the executable to `path` (Executor.serialize_executable)."""
        feed = self._feed_dict(sample_inputs)
        # warm the compile + scope state through one real run
        self._exe.run(self._program, feed=feed, fetch_list=self._fetch_vars,
                      scope=self._scope)
        return self._exe.serialize_executable(
            path, self._program, feed=feed, fetch_list=self._fetch_vars,
            scope=self._scope,
        )

    def _load_executable_meta(self, path):
        import pickle

        with open(path, "rb") as f:
            blob = pickle.load(f)
        self._aot_feed_sig = blob["feed_sig"]
        self._aot_path = path

    def _maybe_load_aot(self, feed):
        if self._aot_feed_sig is None:
            return
        import jax.numpy as jnp

        # signature must be derived exactly as the executor derives it
        # (jnp dtypes — int64 feeds truncate to int32 under default JAX)
        sig = tuple(
            (k, tuple(jnp.asarray(v).shape), str(jnp.asarray(v).dtype))
            for k, v in sorted(feed.items())
        )
        if sig == self._aot_feed_sig:
            self._exe.load_executable(
                self._aot_path, self._program, feed=feed,
                fetch_list=self._fetch_vars, scope=self._scope,
            )
            # installed — later matching runs hit the executor cache
            self._aot_feed_sig = None

    # -- run ------------------------------------------------------------
    def _feed_dict(self, inputs):
        feed = {}
        for name, t in zip(self._feed_names, inputs):
            feed[name] = (
                t.data if isinstance(t, PaddleTensor) else np.asarray(t)
            )
        return feed

    def _bucketed(self, feed):
        """Pad the batch axis up to the configured bucket; returns
        (feed, original_batch or None)."""
        buckets = self._config._batch_buckets
        if not buckets:
            return feed, None
        b = next(iter(feed.values())).shape[0]
        for name, a in feed.items():
            if a.shape[0] != b:
                raise InvalidArgumentError(
                    f"batch bucketing needs a shared leading batch axis; "
                    f"feed {name!r} has {a.shape[0]}, expected {b}"
                )
        target = next((s for s in buckets if s >= b), None)
        if target is None:
            raise PreconditionNotMetError(
                f"batch {b} exceeds the largest configured bucket "
                f"{buckets[-1]}"
            )
        if target == b:
            return feed, None
        padded = {
            k: np.concatenate(
                [a, np.zeros((target - b,) + a.shape[1:], a.dtype)], axis=0
            )
            for k, a in ((k, np.asarray(a)) for k, a in feed.items())
        }
        return padded, b

    def _fetch_batch_leading(self, name):
        """True iff the fetch's DECLARED shape has a dynamic (-1) leading
        dim — the only case where bucket un-padding is verifiably safe.
        Computed once per fetch name (the program is static after
        construction)."""
        cache = self.__dict__.setdefault("_batch_leading_cache", {})
        if name not in cache:
            var = self._program.global_block._find_var_recursive(name)
            declared = getattr(var, "shape", None)
            cache[name] = (
                declared is not None and len(declared) > 0
                and declared[0] in (-1, None),
                declared,
            )
        return cache[name]

    def _unpad_out(self, o, name, orig_b, bucket):
        """Slice bucket padding off a fetch, but only when it is
        VERIFIABLY the batch axis: the declared shape is batch-leading
        (-1 first dim) AND the runtime leading dim equals the bucket. A
        fetch whose leading dim merely coincides with the bucket size, or
        one that reduces over the batch (pad rows leak into the
        reduction), is a contract violation the old shape heuristic hid;
        warn (once per fetch) instead of silently returning wrong data
        (set_batch_buckets contract)."""
        import warnings

        batch_leading, declared = self._fetch_batch_leading(name)
        if (batch_leading and getattr(o, "ndim", 0) > 0
                and o.shape[0] == bucket):
            return o[:orig_b]
        warned = self.__dict__.setdefault("_bucket_warned", set())
        if name in warned:
            return o
        warned.add(name)
        if getattr(o, "ndim", 0) > 0 and o.shape[0] == bucket:
            warnings.warn(
                f"bucketed fetch {name!r} has leading dim == bucket size "
                f"but its declared shape {declared} is not batch-leading; "
                "returning it UN-sliced — restructure the fetch or disable "
                "batch buckets (set_batch_buckets contract)",
                RuntimeWarning, stacklevel=3,
            )
        elif not batch_leading and declared is not None:
            warnings.warn(
                f"bucketed fetch {name!r} (declared shape {declared}) is "
                "not batch-leading; if it reduces over the batch the "
                "zero-pad rows are included (set_batch_buckets contract)",
                RuntimeWarning, stacklevel=3,
            )
        return o

    def run(self, inputs):
        """inputs: list of PaddleTensor/ndarray in feed order -> list of
        PaddleTensor (reference PaddlePredictor::Run)."""
        feed, orig_b = self._bucketed(self._feed_dict(inputs))
        self._maybe_load_aot(feed)
        outs = self._exe.run(
            self._program, feed=feed, fetch_list=self._fetch_vars,
            scope=self._scope,
        )
        names = self.get_output_names()
        if orig_b is not None:
            bucket = next(iter(feed.values())).shape[0]
            outs = [
                self._unpad_out(o, name, orig_b, bucket)
                for o, name in zip(outs, names)
            ]
        return [PaddleTensor(o, name=n) for o, n in zip(outs, names)]

    def run_zero_copy(self, inputs):
        """Like run(), but returns (names, arrays) where `arrays` are
        C-contiguous ndarrays OWNED BY THE PREDICTOR until the next run —
        callers (the C API) read their buffers in place, no copy
        (reference ZeroCopyTensor contract: zero_copy_tensor.cc)."""
        outs = self.run(inputs)
        arrays = [np.ascontiguousarray(t.as_ndarray()) for t in outs]
        self._last_outputs = arrays
        return [t.name for t in outs], arrays


def create_paddle_predictor(config):
    return Predictor(config)
