"""Inference predictor API (reference paddle/fluid/inference/:
AnalysisConfig paddle_analysis_config.h, AnalysisPredictor
analysis_predictor.cc, create_paddle_predictor, PaddleTensor).

TPU-native: load_inference_model gives the pruned Program; the predictor
compiles it once per input-shape set through the ordinary Executor (whole
block -> one XLA executable — the role of the reference's IR pass manager +
NaiveExecutor + TensorRT engines collapses into XLA). Zero-copy: outputs
stay device arrays until .as_ndarray()."""

from __future__ import annotations

import numpy as np


class AnalysisConfig:
    def __init__(self, model_dir=None, params_file=None, model_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self.model_file = model_file
        self._use_feed_fetch_ops = False
        self._switch_ir_optim = True  # accepted; XLA owns optimization

    def disable_glog_info(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = flag

    def switch_use_feed_fetch_ops(self, flag):
        self._use_feed_fetch_ops = flag

    def enable_use_gpu(self, *a, **k):  # API parity: device is the TPU
        pass

    def disable_gpu(self):
        pass


class PaddleTensor:
    """Host-side input/output tensor (reference paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name=""):
        self.name = name
        self.data = np.asarray(data) if data is not None else None

    def as_ndarray(self):
        return np.asarray(self.data)


class Predictor:
    """AnalysisPredictor parity: load once, run many."""

    def __init__(self, config):
        from . import io as _io
        from .framework.executor import Executor
        from .framework.scope import Scope, scope_guard

        if config.model_dir is None:
            raise ValueError("AnalysisConfig.model_dir is required")
        self._scope = Scope()
        self._exe = Executor()
        with scope_guard(self._scope):
            (
                self._program,
                self._feed_names,
                self._fetch_vars,
            ) = _io.load_inference_model(
                config.model_dir,
                self._exe,
                model_filename=getattr(config, "model_file", None),
                params_filename=getattr(config, "params_file", None),
            )

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [
            v if isinstance(v, str) else v.name for v in self._fetch_vars
        ]

    def run(self, inputs):
        """inputs: list of PaddleTensor/ndarray in feed order -> list of
        PaddleTensor (reference PaddlePredictor::Run)."""
        feed = {}
        for name, t in zip(self._feed_names, inputs):
            feed[name] = t.data if isinstance(t, PaddleTensor) else np.asarray(t)
        outs = self._exe.run(
            self._program, feed=feed, fetch_list=self._fetch_vars,
            scope=self._scope,
        )
        return [
            PaddleTensor(o, name=n)
            for o, n in zip(outs, self.get_output_names())
        ]


def create_paddle_predictor(config):
    return Predictor(config)
