"""Python-side streaming metrics (reference python/paddle/fluid/metrics.py:
MetricBase, CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator,
EditDistance, Auc, DetectionMAP).

Host-side accumulators fed with fetched numpy values — deliberately NOT ops
(the in-graph metric ops live in ops/metrics.py: accuracy/auc); these
aggregate across steps/epochs on the host exactly like the reference.
"""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {
            k: v for k, v in self.__dict__.items() if not k.startswith("_")
        }


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision over {0,1} preds/labels (reference :244)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Streaming ROC AUC by thresholded confusion counts (reference :580
    uses the same bucketed estimator)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num + 1, np.int64)
        self._stat_neg = np.zeros(self._num + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip(
            (preds * self._num).astype(np.int64), 0, self._num
        )
        np.add.at(self._stat_pos, idx[labels > 0.5], 1)
        np.add.at(self._stat_neg, idx[labels <= 0.5], 1)

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return float(auc / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, distances, seq_num=None):
        d = np.asarray(distances).reshape(-1)
        self.total += float(d.sum())
        self.count += int(seq_num if seq_num is not None else d.size)

    def eval(self):
        if not self.count:
            raise ValueError("EditDistance: no updates yet")
        return self.total / self.count
