"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle-Fluid
capabilities (reference: /root/reference, see SURVEY.md), built on JAX/XLA.

Architecture (TPU-first, not a port):
  * static graph: Program/Block/Op IR -> whole-block XLA compilation
    (framework/executor.py) instead of per-op kernel dispatch;
  * autodiff: graph-transform append_backward whose grad ops replay forward
    emitters under jax.vjp (framework/backward.py);
  * eager "dygraph" mode with taped autograd (dygraph/);
  * distributed: GSPMD sharding + shard_map collectives over a device Mesh
    (parallel/), replacing NCCL rings and the SSA-graph ParallelExecutor.
"""

from . import core  # noqa: F401  (places, dtypes)
from . import errors  # noqa: F401  (typed error taxonomy, platform/error_codes.proto)
from .core.place import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    cpu_places,
    is_compiled_with_tpu,
    tpu_places,
)
from .framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    in_dygraph_mode,
    program_guard,
    device_guard,
    scope_guard,
)
from . import ops  # noqa: F401  (registers all op emitters)
from .framework.executor import Executor  # noqa: F401
from .framework.backward import append_backward, gradients  # noqa: F401
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import io  # noqa: F401
from . import contrib  # noqa: F401
from . import incubate  # noqa: F401
from . import dygraph  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from . import dataloader  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401  (metrics/histograms/spans/exporters)
from . import analysis  # noqa: F401  (pre-compile static verifier + collective lint)
from . import resilience  # noqa: F401  (retry/backoff, fault injection)
from . import monitor  # noqa: F401  (back-compat facade over observability)
from . import debugger  # noqa: F401  (draw_block_graphviz)
from . import install_check  # noqa: F401  (run_check)
from .flags import get_flags, set_flags  # noqa: F401
from . import metrics  # noqa: F401
from . import nets  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from . import reader  # noqa: F401  (DataLoader + paddle.reader decorators)
from .reader_decorators import batch  # noqa: F401
from . import dataset  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from . import native  # noqa: F401
from . import crypto  # noqa: F401  (model-file encryption, framework/io/crypto)
from . import inference  # noqa: F401
from . import serving  # noqa: F401  (freeze/router/KV-decode serving path)
from . import embedding  # noqa: F401  (fused/cached/sharded sparse tables)
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import tensor  # noqa: F401
from . import tools  # noqa: F401
from .reader import DataLoader  # noqa: F401

# `fluid`-compatible alias so code written against the reference API reads
# naturally: `import paddle_tpu as fluid; fluid.layers.fc(...)`.
fluid = None  # replaced below to avoid circular import confusion
import sys as _sys

fluid = _sys.modules[__name__]

__version__ = "0.1.0"


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data parity: full-shape feed declaration."""
    return layers.data(name, shape, dtype, lod_level=lod_level)
