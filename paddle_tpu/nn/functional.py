"""paddle.nn.functional namespace (reference python/paddle/nn/functional/):
functional aliases of fluid.layers ops."""

from ..layers import (  # noqa: F401
    conv2d,
    dropout,
    elu,
    gelu,
    hard_sigmoid,
    hard_swish,
    leaky_relu,
    log_softmax,
    logsigmoid,
    pool2d,
    relu,
    relu6,
    selu,
    sigmoid,
    sigmoid_cross_entropy_with_logits,
    silu,
    softmax,
    softmax_with_cross_entropy,
    softplus,
    softsign,
    swish,
    tanh,
)
