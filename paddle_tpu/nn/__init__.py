"""paddle.nn 2.0-preview namespace (reference python/paddle/nn/__init__.py:
thin re-exports of fluid layers/dygraph modules)."""

from ..dygraph.nn import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Embedding,
    LayerNorm,
    Linear,
    Pool2D,
)
from ..dygraph.layers import Layer, LayerList, ParameterList, Sequential  # noqa: F401
from . import functional  # noqa: F401
