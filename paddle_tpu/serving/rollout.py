"""Canaried live-model rollout: publish dir → replica set, with rollback.

The publish plane (``fleet/publish.py``) makes versions durable and
subscribers fence their applies; this module decides WHEN each replica
moves, because a fleet that applies every version everywhere at once has
no blast-radius control. :class:`RolloutController` drives the PR-15/17
replica machinery through the canonical staged shape:

1. **Canary** — a new eligible version is applied to ONE replica (the
   apply itself is fenced: a process worker serializes it against its
   batch loop, an in-process :class:`SubscribedRunner` holds its
   dispatch lock), which then soaks under live traffic for
   ``canary_soak_ticks`` polls while the PR-13 Watcher signal (p99
   breach findings), the dispatch/batch error counters, and an optional
   finite-output probe batch all get a veto.
2. **Staged rollout** — a passing canary promotes the version replica by
   replica through drain → apply → restore, each restore re-warming the
   replica's bucket set when the update changed persistable shapes
   (``ReplicaSet.restore_replica(rewarm=True)``; process workers re-warm
   themselves), so compiles never land inside a measured request.
3. **Post-rollout soak** — ``breach_ticks`` consecutive breach polls
   after a fleet-wide rollout trigger **automatic rollback**: every
   replica re-folds to the last-good version (the full-chain downgrade
   path — bitwise the cold load of that version), the bad version is
   recorded in ``blocked.json`` so followers and respawns skip it
   forever, and a FlightRecorder dump preserves the telemetry window
   that convicted it.

A failing canary takes the same rollback path with a one-replica blast
radius. :meth:`freeze`/:meth:`unfreeze` stop new rollouts without
touching serving — the brownout ladder's "freeze publishes" rung wires
here, so an overloaded server stops paying apply stalls exactly when
latency is scarcest.

Counters/gauges: ``publish.canary_starts`` / ``publish.canary_passes``
/ ``publish.canary_fails`` / ``publish.rollouts`` /
``publish.rollbacks`` / ``publish.freezes``, plus the fleet-level
``serving.model_version`` / ``serving.model_staleness_seconds`` gauges
(per-worker twins live in each worker's journal shard).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..errors import InvalidArgumentError
from ..fleet import publish as _publish

__all__ = ["RolloutController", "SubscribedRunner"]

_BREACH_KINDS = ("slo_breach", "step_regression")


class SubscribedRunner:
    """An in-process runner + fenced subscriber, for single-process
    replica sets. ``run`` and ``apply_update`` share one lock — the
    epoch fence: a batch sees the version that was fully applied before
    it started, never a mid-apply mixture (process workers get the same
    guarantee positionally from their single-threaded serve loop)."""

    def __init__(self, runner, subscriber):
        self.runner = runner
        self.subscriber = subscriber
        self.feed_names = tuple(runner.feed_names)
        self.fetch_names = tuple(getattr(runner, "fetch_names", ()))
        self._fence = threading.Lock()

    def sample_spec(self, name):
        return self.runner.sample_spec(name)

    @property
    def version(self):
        return self.subscriber.version

    def run(self, feed):
        with self._fence:
            return self.runner.run(feed)

    def apply_update(self, version=None):
        """Fenced apply; returns the ``applied``-reply shape the process
        fleet's ``apply_update`` message returns, so the rollout
        controller treats both transports uniformly."""
        with self._fence:
            applied = (
                self.subscriber.apply_version(version)
                if version is not None else self.subscriber.poll()
            )
        return {
            "applied": applied,
            "version": self.subscriber.version,
            "staleness_s": self.subscriber.staleness_s(),
            "shapes_changed": bool(self.subscriber.shapes_changed),
            "self_warmed": False,
        }


class RolloutController:
    """Drive canaried rollout + automatic rollback over a replica set.

    ``replica_set`` is a :class:`~paddle_tpu.serving.replica.ReplicaSet`
    (or :class:`~paddle_tpu.serving.fleet.ProcessReplicaSet`) whose
    replicas can apply published versions: in-process replicas wrap
    their runner in :class:`SubscribedRunner`; process fleets spawn
    their workers with ``publish_mode="managed"`` so THIS controller is
    the only thing that moves versions. :meth:`poll` is the control
    tick — pure enough to unit-test, live enough to thread.
    """

    def __init__(self, replica_set, publish_dir, watcher=None,
                 canary_soak_ticks=2, post_soak_ticks=4, breach_ticks=2,
                 error_counters=("serving.dispatch_failures",
                                 "serving.worker.batch_errors"),
                 probe_feed=None, interval=0.5, clock=time.time):
        if int(canary_soak_ticks) < 1 or int(breach_ticks) < 1:
            raise InvalidArgumentError(
                "canary_soak_ticks and breach_ticks must be >= 1"
            )
        self.replica_set = replica_set
        self.publish_dir = publish_dir
        self.watcher = watcher
        self.canary_soak_ticks = int(canary_soak_ticks)
        self.post_soak_ticks = int(post_soak_ticks)
        self.breach_ticks = int(breach_ticks)
        self.error_counters = tuple(error_counters)
        self.probe_feed = probe_feed
        self.interval = float(interval)
        self._clock = clock
        self.version = None        # fleet-wide rolled-out (last good)
        self.commit_time = None
        self.state = "idle"        # idle | canary | post
        self._candidate = None
        self._canary = None
        self._soak_left = 0
        self._post_left = 0
        self._breach_streak = 0
        self._err_base = None
        self.frozen = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- publish-control surface (brownout's + storage's freeze rung) ------
    def freeze(self, reason=None):
        """Stop advancing to new versions. `reason` tags the per-cause
        counter (``publish.freezes.<reason>``) so a brownout freeze and a
        storage ``disk_pressure`` freeze stay distinguishable in the
        journal; callers without a cause omit it."""
        from .. import observability as _obs

        with self._lock:
            if not self.frozen:
                self.frozen = True
                _obs.add("publish.freezes")
                if reason:
                    _obs.add(f"publish.freezes.{reason}")
        _obs.set_gauge("publish.frozen", 1.0)

    def unfreeze(self):
        from .. import observability as _obs

        with self._lock:
            self.frozen = False
        _obs.set_gauge("publish.frozen", 0.0)

    # -- signal ------------------------------------------------------------
    def _errors_now(self):
        from .. import observability as _obs

        counters = _obs.get_counters()
        return sum(counters.get(c, 0) for c in self.error_counters)

    def _probe_ok(self, rep_name):
        """Run the probe batch through one replica; False on nonfinite
        outputs or a probe failure (both are canary vetoes)."""
        if self.probe_feed is None:
            return True
        from .. import observability as _obs

        rep = self.replica_set._find(rep_name)
        try:
            outs = rep.runner.run(self.probe_feed)
        except Exception:
            return False
        for out in outs or ():
            arr = np.asarray(out)
            if np.issubdtype(arr.dtype, np.inexact) and not np.all(
                np.isfinite(arr)
            ):
                _obs.add("publish.nonfinite_probes")
                return False
        return True

    def _breach(self, canary=None):
        """One soak observation: watcher findings/latch, error-counter
        delta since the soak started, probe verdict."""
        findings = self.watcher.poll() if self.watcher is not None else ()
        if any(f.get("kind") in _BREACH_KINDS for f in findings or ()):
            return True
        if self.watcher is not None and getattr(
            self.watcher, "breaching", False
        ):
            return True
        if self._err_base is not None and (
            self._errors_now() > self._err_base
        ):
            return True
        if canary is not None and not self._probe_ok(canary):
            return True
        return False

    # -- apply plumbing ----------------------------------------------------
    def _apply(self, rep_name, version):
        """Apply `version` on one replica over whichever transport it
        has; returns the normalized ``applied`` reply."""
        fleet_apply = getattr(self.replica_set, "apply_update", None)
        if fleet_apply is not None:
            reply = fleet_apply(rep_name, version)
            reply.setdefault("self_warmed", True)
            return reply
        runner = self.replica_set._find(rep_name).runner
        apply_update = getattr(runner, "apply_update", None)
        if apply_update is None:
            raise InvalidArgumentError(
                f"replica {rep_name!r} can apply no published updates "
                "(wrap its runner in SubscribedRunner, or use a "
                "ProcessReplicaSet with publish_dir)"
            )
        return apply_update(version)

    def _replica_names(self):
        with self.replica_set._lock:
            return [
                rep.name for rep in self.replica_set._order
                if not rep.draining
            ]

    def _staged(self, names, version):
        """Drain → apply → restore each replica in turn; the set keeps
        serving on the others throughout."""
        for name in names:
            self.replica_set.drain_replica(name)
            try:
                reply = self._apply(name, version)
            except Exception:
                # a replica that cannot take the version stays consistent
                # on its old one; restore it and surface the failure
                self.replica_set.restore_replica(name)
                raise
            rewarm = bool(reply.get("shapes_changed")) and not bool(
                reply.get("self_warmed")
            )
            self.replica_set.restore_replica(name, rewarm=rewarm)

    def _adopt(self, version):
        from .. import observability as _obs

        self.version = version
        try:
            self.commit_time = _publish.read_commit(
                self.publish_dir, version
            ).get("created_at")
        except Exception:
            self.commit_time = None
        _obs.set_gauge("serving.model_version", float(version))
        self._publish_staleness()

    def _publish_staleness(self):
        from .. import observability as _obs

        if self.commit_time is not None:
            _obs.set_gauge(
                "serving.model_staleness_seconds",
                max(0.0, self._clock() - float(self.commit_time)),
            )

    def _rollback(self, names, bad, trigger):
        """The auto-rollback path (canary-fail AND post-rollout breach):
        re-fold every affected replica onto the last-good version, block
        the bad one fleet-wide, and dump the flight recorder."""
        from .. import observability as _obs
        from ..observability import recorder as _recorder

        last_good = self.version
        rolled = []
        if last_good is not None:
            self._staged(names, last_good)
            rolled = list(names)
        else:
            # no good version to re-fold to: keep the poisoned replicas
            # out of rotation rather than serving a convicted model
            for name in names:
                self.replica_set.drain_replica(name)
            _obs.add("publish.canary_stranded")
        _publish.block_version(self.publish_dir, bad)
        _obs.add("publish.rollbacks")
        _recorder.flight_dump("publish_rollback", detail={
            "trigger": trigger, "bad_version": int(bad),
            "rolled_back_to": last_good, "replicas": rolled,
        })

    # -- control tick ------------------------------------------------------
    def poll(self):
        """One rollout decision tick; returns the controller state."""
        from .. import observability as _obs

        self._publish_staleness()
        if self.state == "idle":
            if self.frozen:
                return self.state
            target = _publish.latest_version(self.publish_dir)
            if target is None or target == self.version:
                return self.state
            names = self._replica_names()
            if not names:
                return self.state
            canary = names[0]
            self._err_base = self._errors_now()
            try:
                self._apply(canary, target)
            except Exception:
                # the subscriber's fence kept the canary on its old
                # version; convict the bundle without any rollback
                _publish.block_version(self.publish_dir, target)
                _obs.add("publish.canary_fails")
                return self.state
            self._candidate = target
            self._canary = canary
            self._soak_left = self.canary_soak_ticks
            self.state = "canary"
            _obs.add("publish.canary_starts")
            return self.state
        if self.state == "canary":
            if self._breach(canary=self._canary):
                self._rollback([self._canary], self._candidate, "canary")
                _obs.add("publish.canary_fails")
                self._candidate = self._canary = None
                self.state = "idle"
                return self.state
            self._soak_left -= 1
            if self._soak_left > 0:
                return self.state
            _obs.add("publish.canary_passes")
            rest = [
                n for n in self._replica_names() if n != self._canary
            ]
            try:
                self._staged(rest, self._candidate)
            except Exception:
                # mid-rollout failure: the fleet is split — roll the
                # already-updated replicas back rather than serving two
                # versions indefinitely
                done = [self._canary] + [
                    n for n in rest
                    if self._version_of(n) == self._candidate
                ]
                self._rollback(done, self._candidate, "staged_rollout")
                self._candidate = self._canary = None
                self.state = "idle"
                return self.state
            self._adopt(self._candidate)
            self._candidate = self._canary = None
            self._post_left = self.post_soak_ticks
            self._breach_streak = 0
            self.state = "post"
            _obs.add("publish.rollouts")
            return self.state
        if self.state == "post":
            if self._breach():
                self._breach_streak += 1
            else:
                self._breach_streak = 0
            if self._breach_streak >= self.breach_ticks:
                bad = self.version
                # the previous good version is the rollback target
                self.version, self.commit_time = None, None
                prior = [
                    v for v in _publish.committed_versions(
                        self.publish_dir
                    )
                    if v < bad and v not in _publish.read_blocked(
                        self.publish_dir
                    )
                ]
                self.version = prior[-1] if prior else None
                if self.version is not None:
                    self._adopt(self.version)
                self._rollback(
                    self._replica_names(), bad, "post_rollout"
                )
                self._breach_streak = 0
                self.state = "idle"
                return self.state
            self._post_left -= 1
            if self._post_left <= 0:
                self.state = "idle"
            return self.state
        return self.state

    def _version_of(self, name):
        runner = self.replica_set._find(name).runner
        return getattr(runner, "version", None)

    # -- live wiring -------------------------------------------------------
    def start(self):
        """Poll on a daemon thread every ``interval`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serving-rollout"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception:
                pass  # a broken tick must not kill the controller