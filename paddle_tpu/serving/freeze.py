"""Graph freezing: training Program -> pure inference Program.

``freeze_program`` is the serving analogue of the reference's
``load_inference_model`` pruning (PAPER.md: the fluid predictor pipeline):
clone the graph in test mode, backward-slice it to the requested fetches
(which drops backward/optimizer/loss-scale ops — they feed no fetch), and
*verify* that nothing training-only survived (the
``training-op-in-inference`` structural finding; strict verify refuses to
compile a bad freeze). The frozen program is marked ``_is_inference`` so
the Executor traces it in test mode and the static verifier holds it to
the inference contract.

INT8 leg: ``int8_scales=`` bakes slim's calibrated PTQ activation scales
into the frozen graph through the same ``contrib/slim/quantization.py``
walker QAT uses (weights quantize channel-wise at apply time), so the
served graph carries its quant-dequant chain with zero training
leftovers.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class FrozenModel:
    """A servable graph: the frozen Program plus its feed/fetch contract."""

    program: object
    feed_names: tuple
    fetch_names: tuple
    # set when the INT8 leg baked calibrated scales into the graph
    int8: bool = False
    meta: dict = field(default_factory=dict)

    def save(self, dirname, scope=None):
        """Export with ``io.save_inference_model`` semantics (program +
        CRC-manifested params) for a later ``load_frozen``."""
        from .. import io as _io
        from ..framework.scope import global_scope, scope_guard

        with scope_guard(scope or global_scope()):
            return _io.save_inference_model(
                dirname, list(self.feed_names),
                [self.program.global_block.var(n) for n in self.fetch_names],
                main_program=self.program,
            )


def _referenced_names(program):
    names = set()
    for blk in program.blocks:
        for op in blk.ops:
            names.update(op.input_names())
            names.update(op.output_names())
    return names


def _strip_unused_vars(program, keep_names=(), referenced=None):
    """Drop Variable metadata nothing references after the prune: frozen
    graphs travel (pickled into model dirs, shipped to servers), and a
    training graph's optimizer-state/grad var table is dead weight there.
    `keep_names` (the feed contract) always survives; pass `referenced`
    to reuse an already-computed name walk."""
    if referenced is None:
        referenced = _referenced_names(program)
    keep = referenced | set(keep_names)
    removed = 0
    for blk in program.blocks:
        for name in [n for n in blk.vars if n not in keep]:
            del blk.vars[name]
            removed += 1
    return removed


def freeze_program(program, fetch_list, feed_names=(), int8_scales=None,
                   quantizable_ops=None, verify=True):
    """Freeze `program` to the pure inference subgraph producing
    `fetch_list`.

    Returns a :class:`FrozenModel`. The frozen Program:

    * runs in test mode (``clone(for_test=True)`` flipped is_test ops;
      ``_is_inference`` makes the Executor trace with ``is_test=True``);
    * contains only ops on the feed->fetch path (``io.prune`` backward
      slice — backward ``__vjp__``/grad ops, optimizer updates, and the
      AMP loss-scale automaton all feed no fetch, so they fall away);
    * passes the structural verifier's ``training-op-in-inference``
      check (raises ``ProgramVerifyError`` if a training op survived —
      e.g. a fetch that reaches through an optimizer output).

    `int8_scales` ({var_name: calibrated scale}) routes quantizable-op
    activations through fixed-scale quant-dequant ops and weights through
    channel-wise abs-max quant-dequant (slim's PTQ bake), producing the
    INT8-annotated serving graph.
    """
    from .. import observability as _obs
    from ..io import prune

    fetch_list = list(fetch_list)
    test_prog = program.clone(for_test=True)
    fetch_names = [
        v.name if hasattr(v, "name") else str(v) for v in fetch_list
    ]
    targets = [test_prog.global_block.var(n) for n in fetch_names]
    explicit_feeds = tuple(feed_names)
    all_data = tuple(
        v.name for v in test_prog.list_vars() if v.is_data
    )
    n_before = sum(len(b.ops) for b in test_prog.blocks)
    frozen = prune(test_prog, targets, feeds=explicit_feeds or all_data)
    frozen._is_inference = True
    referenced = None
    if explicit_feeds:
        feed_names = explicit_feeds
    else:
        # the default feed contract is the data vars the PRUNED graph
        # actually reads — a training graph's label inputs feed only the
        # loss and must not survive into the serving contract (a router
        # request would need a label array per submit)
        referenced = _referenced_names(frozen)
        feed_names = tuple(n for n in all_data if n in referenced)

    if int8_scales is not None:
        from ..contrib.slim.quantization import (QUANTIZABLE_OPS,
                                                 bake_ptq_scales)

        n_qdq = bake_ptq_scales(
            frozen, int8_scales,
            quantizable_ops=quantizable_ops or QUANTIZABLE_OPS,
        )
        _obs.add("serving.freeze_int8_qdq_ops", n_qdq)
        referenced = None  # the bake added qdq ops/vars: re-walk

    removed_vars = _strip_unused_vars(
        frozen, keep_names=feed_names, referenced=referenced
    )
    frozen._bump()
    n_after = sum(len(b.ops) for b in frozen.blocks)
    _obs.add("serving.programs_frozen")
    _obs.add("serving.freeze_ops_pruned", max(0, n_before - n_after))

    if verify:
        from ..analysis import verify_program
        from ..analysis.findings import TRAINING_OP_IN_INFERENCE
        from ..errors import ProgramVerifyError

        report = verify_program(
            frozen, feed_names, fetch_names,
            families=("structural",),
        )
        survivors = report.by_category(TRAINING_OP_IN_INFERENCE)
        if survivors:
            raise ProgramVerifyError(
                "freeze_program left training-only ops in the inference "
                "graph (a fetch reaches through training state?):\n"
                + "\n".join("  " + f.format() for f in survivors),
                findings=report.findings,
                op=survivors[0].op_type,
            )
    return FrozenModel(
        program=frozen,
        feed_names=tuple(feed_names),
        fetch_names=tuple(fetch_names),
        int8=int8_scales is not None,
        meta={
            "ops_pruned": max(0, n_before - n_after),
            "vars_stripped": removed_vars,
        },
    )


def load_frozen(dirname, scope=None, executor=None):
    """Load a ``FrozenModel.save`` / ``io.save_inference_model`` export
    as a servable :class:`FrozenModel`."""
    from .. import io as _io
    from ..framework.scope import global_scope, scope_guard

    with scope_guard(scope or global_scope()):
        # load_inference_model marks the program _is_inference itself
        program, feed_names, fetch_names = _io.load_inference_model(
            dirname, executor
        )
    return FrozenModel(
        program=program,
        feed_names=tuple(feed_names),
        fetch_names=tuple(fetch_names),
    )
