"""Process-isolated serving worker: one frozen executable per process.

``python -m paddle_tpu.serving.worker --model-dir D --ready-file F`` is
the child half of the process replica fleet (``serving/fleet.py``): it
loads a saved frozen model (program + checkpointed params) into its own
Scope/Executor, warms the configured batch buckets, then serves batches
over a length-prefixed socket protocol until told to stop. Process
isolation is the point — one GIL, one heap, one fault blast radius per
replica, so a SIGKILL (or a native crash) takes out exactly one worker
and the parent's supervisor respawns it while traffic fails over.

**Framing.** Every message is an 8-byte big-endian length followed by a
pickled payload dict. :func:`send_msg` / :func:`recv_msg` are the whole
wire format; both refuse frames above ``max_frame`` (default 64 MiB,
``PADDLE_TPU_MAX_FRAME_BYTES``) and surface torn reads as a typed
:class:`TransportError` — a peer death mid-frame is an error, never a
hang. Both sides pass the ``serving.transport.send`` /
``serving.transport.recv`` chaos seams, so transport failure (raise or
hang kinds) is injectable without killing a process.

**Protocol.** Requests carry a per-message ``id`` the reply must echo —
after an attempt timeout abandons a batch, a late straggler reply on the
same connection is recognized as stale by id and discarded instead of
desynchronizing the stream. Kinds: ``run`` (one padded bucket batch;
reply ``result`` with the fetch outputs or ``error`` with the typed
exception name), ``warmup`` (same dispatch, warmup accounting),
``ping``/``pong`` (liveness + stats), ``shutdown`` (reply ``bye``, exit
0 — the deliberate scale-in path).

**Contracts honored.** The worker publishes PR-3 heartbeats
(``hb_rank{K}`` via ``PADDLE_HEARTBEAT_DIR``; per-batch beats plus a
periodic idle ``touch`` so an idle worker is never mistaken for hung)
and PR-16 telemetry journals (``PADDLE_TPU_TELEMETRY_DIR``, auto-wired
by ``Executor.__init__``), and rides the SIGTERM→drain→exit-75
preemption contract: SIGTERM finishes the in-flight batch, stops
accepting, and exits ``PREEMPTION_EXIT_CODE``. An explicit ``--port``
that loses a bind race (double spawn, stale owner) falls back to an
ephemeral port and reports the REAL port in the ready file
(``serving.worker.port_fallbacks``) instead of dying or serving nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import socket
import struct
import sys
import tempfile
import threading
import time

from ..errors import UnavailableError

__all__ = [
    "MAX_FRAME_ENV",
    "TransportError",
    "bind_serving_socket",
    "default_max_frame",
    "recv_msg",
    "send_msg",
    "worker_main",
]

_HEADER = struct.Struct("!Q")
MAX_FRAME_ENV = "PADDLE_TPU_MAX_FRAME_BYTES"
_DEFAULT_MAX_FRAME = 64 * 1024 * 1024


class TransportError(UnavailableError):
    """Worker transport failure: torn frame, oversized frame, or a peer
    that vanished mid-message. An UnavailableError, so the replica-set
    failover machinery classifies it as retryable-on-another-replica."""


def default_max_frame():
    try:
        return int(os.environ.get(MAX_FRAME_ENV, _DEFAULT_MAX_FRAME))
    except ValueError:
        return _DEFAULT_MAX_FRAME


def send_msg(sock, obj, max_frame=None):
    """Frame + send one message dict. Refuses payloads above `max_frame`
    BEFORE writing anything, so an oversized batch can never leave a
    half-written frame poisoning the stream."""
    from ..resilience.faults import fault_point

    fault_point("serving.transport.send")
    limit = default_max_frame() if max_frame is None else int(max_frame)
    payload = pickle.dumps(obj, protocol=4)
    if len(payload) > limit:
        raise TransportError(
            f"refusing to send {len(payload)}-byte frame "
            f"(max_frame {limit}); batch too large for the transport"
        )
    try:
        sock.sendall(_HEADER.pack(len(payload)) + payload)
    except socket.timeout:
        # a timeout is NOT a transport failure: the caller classifies it
        # (the fleet client types it ExecutionTimeoutError, the worker's
        # idle loop just polls again)
        raise
    except OSError as exc:
        raise TransportError(f"send failed: {exc}") from exc


def _recv_exact(sock, n, allow_eof=False):
    """Read exactly `n` bytes. Clean EOF before the first byte returns
    None when `allow_eof` (the peer closed between frames); EOF anywhere
    else is a torn frame and raises typed."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            # at a frame boundary (allow_eof marks the header read) and
            # zero bytes in: a pure idle timeout, safe to poll again —
            # anywhere else the stream is desynchronized mid-message
            if allow_eof and not buf:
                raise
            raise TransportError(
                f"timed out mid-frame ({len(buf)}/{n} bytes read); "
                "stream desynchronized"
            )
        except OSError as exc:
            raise TransportError(f"recv failed: {exc}") from exc
        if not chunk:
            if allow_eof and not buf:
                return None
            raise TransportError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes read); "
                "torn message"
            )
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock, max_frame=None):
    """Receive one framed message dict, or None on clean EOF at a frame
    boundary. A length prefix above `max_frame` is refused typed (the
    connection is unusable afterwards — the caller must close it)."""
    from ..resilience.faults import fault_point

    fault_point("serving.transport.recv")
    limit = default_max_frame() if max_frame is None else int(max_frame)
    head = _recv_exact(sock, _HEADER.size, allow_eof=True)
    if head is None:
        return None
    (length,) = _HEADER.unpack(head)
    if length > limit:
        raise TransportError(
            f"refusing {length}-byte frame (max_frame {limit}); "
            "oversized or corrupt length prefix"
        )
    payload = _recv_exact(sock, length)
    try:
        return pickle.loads(payload)
    except Exception as exc:
        raise TransportError(f"undecodable frame: {exc}") from exc


def bind_serving_socket(host="127.0.0.1", port=0, backlog=4):
    """Bind + listen; an explicit `port` that is already taken (double
    spawn, stale owner holding it) falls back to an ephemeral one instead
    of dying — the ready file carries the REAL port, so the parent never
    needed the requested number to be honored. Returns (socket, port)."""
    from .. import observability as _obs

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        srv.bind((host, int(port)))
    except OSError:
        if not port:
            srv.close()
            raise
        _obs.add("serving.worker.port_fallbacks")
        print(
            f"[serving.worker] port {port} unavailable; "
            "falling back to an ephemeral port",
            file=sys.stderr,
        )
        srv.bind((host, 0))
    srv.listen(backlog)
    return srv, srv.getsockname()[1]


def _write_ready(path, payload):
    """Atomic temp+replace publish (the PR-2 idiom): the parent polling
    for readiness never reads a torn JSON."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=".ready.tmp."
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.serving.worker")
    p.add_argument("--model-dir", required=True,
                   help="FrozenModel.save() directory (program + params)")
    p.add_argument("--ready-file", required=True,
                   help="where to publish {pid, port, contract} once "
                        "listening, loaded, and warm")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral; a taken explicit "
                        "port falls back to ephemeral)")
    p.add_argument("--name", default="w0", help="replica name (logs)")
    p.add_argument("--warm-buckets", default="",
                   help="comma-separated batch sizes to warm (compile) "
                        "before publishing readiness — a respawned "
                        "worker re-warms itself here, so it rejoins "
                        "rotation hot")
    p.add_argument("--attempt", type=int, default=0,
                   help="restart attempt number (supervisor bookkeeping)")
    p.add_argument("--publish-dir", default="",
                   help="live model publish dir to subscribe to; on "
                        "(re)spawn the worker catches up to the newest "
                        "committed version BEFORE publishing readiness, "
                        "so a corpse killed mid-apply rejoins bitwise "
                        "equal to a cold load of that version")
    p.add_argument("--publish-poll", type=float, default=0.5,
                   help="seconds between publish-dir polls in follow "
                        "mode (updates apply between batches — the "
                        "torn-read fence)")
    p.add_argument("--publish-mode", default="follow",
                   choices=("follow", "managed"),
                   help="follow: auto-apply new versions between "
                        "batches; managed: apply only on explicit "
                        "apply_update messages (canaried rollout)")
    return p.parse_args(argv)


class _WorkerState:
    """The loaded model + serving loop state for one worker process."""

    def __init__(self, args):
        from ..framework.executor import Executor
        from ..framework.scope import Scope
        from .freeze import load_frozen
        from .router import FrozenRunner

        self.args = args
        self.scope = Scope()
        self.executor = Executor()
        frozen = load_frozen(
            args.model_dir, scope=self.scope, executor=self.executor
        )
        self.runner = FrozenRunner(
            frozen, executor=self.executor, scope=self.scope
        )
        self.batches = 0
        self.draining = threading.Event()
        self.heartbeat = self._make_heartbeat()
        # live publish plane: subscribe BEFORE warmup/readiness, so a
        # respawned corpse (even one SIGKILLed mid-apply) rejoins on the
        # last committed version — cold frozen load + committed chain =
        # bitwise-equal to a cold load of that version by construction
        self.subscriber = None
        self._follow = False
        self._poll_s = max(0.05, float(getattr(args, "publish_poll", 0.5)))
        self._next_poll = 0.0
        publish_dir = getattr(args, "publish_dir", "")
        if publish_dir:
            from ..fleet.publish import ModelSubscriber

            self.subscriber = ModelSubscriber(
                publish_dir, main_program=frozen.program,
                scope=self.scope, heartbeat=self.heartbeat,
                name=args.name,
            )
            self._follow = getattr(
                args, "publish_mode", "follow"
            ) == "follow"
            self.subscriber.poll()
        # warm the configured buckets NOW, before readiness: a cold
        # worker entering rotation would pay its compiles inside a
        # user-visible request (the PR-6 warmup lesson), and a respawned
        # corpse re-warms here with no parent involvement
        buckets = [
            int(b) for b in args.warm_buckets.split(",") if b.strip()
        ]
        for b in buckets:
            self.runner.run(self._zero_feed(b))
        self.warmed = tuple(buckets)

    def _zero_feed(self, batch):
        import numpy as np

        from ..core.dtypes import to_numpy_dtype

        feed = {}
        for name in self.runner.feed_names:
            shape, dtype = self.runner.sample_spec(name)
            feed[name] = np.zeros((batch,) + shape, to_numpy_dtype(dtype))
        return feed

    def _rewarm(self):
        """Re-compile the warmed buckets after a shape-changing apply —
        outside any measured request (the satellite-2 contract)."""
        from .. import observability as _obs

        for b in self.warmed:
            try:
                self.runner.run(self._zero_feed(b))
            except Exception:
                break
        if self.warmed:
            _obs.add("serving.worker.rewarms")

    def _after_apply(self):
        if self.subscriber is not None and self.subscriber.shapes_changed:
            self._rewarm()

    def maybe_follow(self):
        """Follow-mode poll, called ONLY between protocol messages — the
        serve loop is single-threaded, so this placement IS the epoch
        fence: no batch can observe a half-applied version."""
        from .. import observability as _obs

        if self.subscriber is None or not self._follow:
            return None
        now = time.monotonic()
        if now < self._next_poll:
            return None
        self._next_poll = now + self._poll_s
        try:
            applied = self.subscriber.poll()
        except Exception:
            # the fence restored the old version; retry next poll (an
            # injected once-only fault heals, a bad bundle gets blocked
            # by the rollout controller)
            _obs.add("publish.follow_failures")
            return None
        if applied is not None:
            self._after_apply()
        return applied

    def digest(self):
        """CRC32 per scope-resident persistable of the frozen program —
        the cross-process bitwise-equality surface (CI compares a
        delta-updated worker against a cold fold of the same version)."""
        from .. import io as _io

        out = {}
        for var in self.runner.frozen.program.list_vars():
            if not getattr(var, "persistable", False) or getattr(
                var, "is_data", False
            ):
                continue
            val = self.scope.find_var(var.name)
            if val is not None:
                out[var.name] = _io._array_entry(val)["crc32"]
        return out

    def _make_heartbeat(self):
        from ..resilience.health import HEARTBEAT_DIR_ENV, Heartbeat

        if not os.environ.get(HEARTBEAT_DIR_ENV):
            return None
        return Heartbeat()

    def contract(self):
        """The runner surface the parent needs without loading the model:
        feed/fetch names and per-sample specs (dtype as a numpy name)."""
        from ..core.dtypes import convert_dtype

        return {
            "feed_names": list(self.runner.feed_names),
            "fetch_names": list(self.runner.fetch_names),
            "sample_specs": {
                n: [list(self.runner.sample_spec(n)[0]),
                    convert_dtype(self.runner.sample_spec(n)[1])]
                for n in self.runner.feed_names
            },
            "warmed_buckets": list(self.warmed),
        }

    def handle(self, msg):
        """Dispatch one protocol message -> reply dict (never raises for
        model-side failures: those travel as typed ``error`` replies)."""
        from .. import observability as _obs

        kind = msg.get("kind")
        mid = msg.get("id")
        if kind in ("run", "warmup"):
            try:
                outs = self.runner.run(msg["feed"])
            except Exception as exc:  # typed name travels; process lives
                _obs.add("serving.worker.batch_errors")
                return {
                    "kind": "error", "id": mid,
                    "etype": type(exc).__name__, "msg": str(exc),
                }
            self.batches += 1
            _obs.add("serving.worker.batches")
            if self.heartbeat is not None:
                try:
                    self.heartbeat.beat()
                except Exception:
                    pass  # a broken beat must not fail a served batch
            return {"kind": "result", "id": mid, "outs": list(outs)}
        if kind == "ping":
            pong = {
                "kind": "pong", "id": mid, "pid": os.getpid(),
                "batches": self.batches,
            }
            if self.subscriber is not None:
                pong["model_version"] = self.subscriber.version
                pong["staleness_s"] = self.subscriber.staleness_s()
            return pong
        if kind == "apply_update":
            # handled between batches by construction (one message at a
            # time on this loop) — the same fence follow-mode polls use
            if self.subscriber is None:
                return {
                    "kind": "error", "id": mid,
                    "etype": "PreconditionNotMetError",
                    "msg": "worker has no --publish-dir subscription",
                }
            version = msg.get("version")
            try:
                applied = (
                    self.subscriber.apply_version(version)
                    if version is not None else self.subscriber.poll()
                )
            except Exception as exc:
                _obs.add("serving.worker.apply_errors")
                return {
                    "kind": "error", "id": mid,
                    "etype": type(exc).__name__, "msg": str(exc),
                }
            if applied is not None:
                self._after_apply()
            return {
                "kind": "applied", "id": mid, "applied": applied,
                "version": self.subscriber.version,
                "staleness_s": self.subscriber.staleness_s(),
                "shapes_changed": bool(self.subscriber.shapes_changed),
            }
        if kind == "digest":
            reply = {"kind": "digest", "id": mid, "crc": self.digest()}
            if self.subscriber is not None:
                reply["version"] = self.subscriber.version
            return reply
        if kind == "shutdown":
            return {"kind": "bye", "id": mid}
        return {
            "kind": "error", "id": mid, "etype": "InvalidArgumentError",
            "msg": f"unknown message kind {kind!r}",
        }


def _idle_pulse(state, interval):
    """Daemon: periodic heartbeat ``touch`` so an idle worker (no batches,
    hence no per-batch beats) is never declared hung by the supervisor's
    stale-beat watchdog."""
    while not state.draining.wait(interval):
        if state.heartbeat is not None:
            try:
                state.heartbeat.touch()
            except Exception:
                pass


def worker_main(argv=None):
    from ..resilience.health import PREEMPTION_EXIT_CODE

    args = parse_args(argv)
    srv, port = bind_serving_socket(args.host, args.port)
    state = _WorkerState(args)

    import signal as _signal

    def _on_sigterm(signum, frame):
        # drain contract: finish the in-flight batch (the serve loop
        # checks the flag between messages), then exit 75
        state.draining.set()

    _signal.signal(_signal.SIGTERM, _on_sigterm)
    threading.Thread(
        target=_idle_pulse, args=(state, 1.0), daemon=True,
        name="worker-idle-pulse",
    ).start()
    if state.heartbeat is not None:
        state.heartbeat.touch()

    _write_ready(args.ready_file, {
        "pid": os.getpid(), "host": args.host, "port": port,
        "name": args.name, "attempt": int(args.attempt),
        **state.contract(),
    })
    print(
        f"[serving.worker {args.name}] ready on {args.host}:{port} "
        f"(pid {os.getpid()}, attempt {args.attempt}, "
        f"warmed {state.warmed})",
        file=sys.stderr, flush=True,
    )

    # accept loop: one parent connection at a time; a parent reconnect
    # (after its side of a torn stream) just lands back here
    srv.settimeout(0.25)
    rc = 0
    try:
        while not state.draining.is_set():
            state.maybe_follow()
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            with conn:
                conn.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                conn.settimeout(0.25)
                bye = False
                while not state.draining.is_set() and not bye:
                    # between-messages = between-batches: the only place
                    # a followed update may apply (torn-read fence)
                    state.maybe_follow()
                    try:
                        msg = recv_msg(conn)
                    except socket.timeout:
                        continue
                    except TransportError:
                        break  # parent vanished; back to accept
                    if msg is None:
                        break  # clean disconnect
                    reply = state.handle(msg)
                    try:
                        send_msg(conn, reply)
                    except (TransportError, socket.timeout):
                        break  # parent gone or wedged; back to accept
                    if reply.get("kind") == "bye":
                        bye = True
                if bye:
                    return 0
    finally:
        try:
            srv.close()
        except OSError:
            pass
    if state.draining.is_set():
        rc = PREEMPTION_EXIT_CODE
    return rc


if __name__ == "__main__":
    sys.exit(worker_main())
