"""Inference serving subsystem: checkpoint -> frozen graph -> QPS.

The training stack (PRs 1-7) ends at a checkpoint; this package is the
path from that checkpoint to traffic (ROADMAP item 1):

* :mod:`freeze` — ``freeze_program(program, fetch_list)`` prunes
  loss/optimizer/backward ops into a pure inference Program (optional
  INT8 leg baking slim's calibrated PTQ scales into the frozen graph).
* :mod:`router` — ``Server``/``Endpoint``: a request router with
  continuous batching over bucketed shapes. Requests land in per-endpoint
  queues; a scheduler thread forms batches under a max-wait deadline,
  pads to the nearest compiled bucket (so the executor's per-(program,
  feed-shapes, fetch-set) executable LRU amortizes compiles), and
  resolves per-request futures.
* :mod:`generate` — ``GPTGenerator``: the KV-cache decode path (prefill
  + single-token decode programs sharing cache persistables in scope;
  O(1) recompute per generated token).
* :mod:`replica` — ``ReplicaSet``: N replicas behind one endpoint with
  per-replica circuit breakers, watchdog-bounded dispatch, exactly-once
  batch failover, and per-replica drain.
* :mod:`worker` + :mod:`fleet` — ``ProcessReplicaSet``: the same runner
  surface over N process-isolated ``serving.worker`` children (length-
  prefixed socket protocol, supervised restart with full-jitter backoff,
  least-inflight routing, failover under real SIGKILL) plus
  ``FleetAutoscaler``, the brownout ladder's capacity-first rung.
* :mod:`brownout` — ``BrownoutController``: turns sustained watcher
  ``slo_breach``/``step_regression`` findings into an adaptive
  degradation ladder (shrink max-wait, cap buckets, shed the background
  class) that re-arms when p99 recovers.

Fault domain: requests carry deadlines (``submit(deadline_ms=)``;
expired work is dropped pre-dispatch with a typed
``errors.DeadlineExceededError``) and priority classes
(``INTERACTIVE``/``BATCH``/``BACKGROUND``; the lowest class sheds first
under pressure, ``errors.RequestShedError``). Goodput — in-deadline
completions — is first-class telemetry (``serving.goodput``).

Lifecycle: ``serving.*`` counters/gauges/histograms ride the PR-1
observability registry; ``Server.drain()`` / SIGTERM ride the PR-3
preemption contract (stop admitting, flush in-flight batches, exit 75;
the drain budget pro-rates across endpoints).
"""

from __future__ import annotations

from .brownout import BrownoutController  # noqa: F401
from .fleet import FleetAutoscaler, ProcessReplicaSet  # noqa: F401
from .freeze import FrozenModel, freeze_program, load_frozen  # noqa: F401
from .generate import GPTGenerator  # noqa: F401
from .replica import ReplicaSet  # noqa: F401
from .router import (  # noqa: F401
    BACKGROUND,
    BATCH,
    INTERACTIVE,
    Endpoint,
    EndpointConfig,
    Server,
    install_preemption_handler,
)
