"""Process-isolated replica fleet: N worker processes behind one runner.

PR 15's :class:`~paddle_tpu.serving.replica.ReplicaSet` hardened the
serving fault domain, but every replica still shares one Python process —
one GIL, one heap, one blast radius — so "failover" could only ever be an
injected exception. :class:`ProcessReplicaSet` keeps the exact same
runner surface (an ``Endpoint`` fronts it unchanged) and moves each
replica into its own ``python -m paddle_tpu.serving.worker`` process:

* **Supervised lifecycle** — workers are spawned and watched by the
  :class:`~paddle_tpu.resilience.supervisor.Supervisor` extracted from
  the elastic launcher: bounded full-jitter restart backoff, stale
  heartbeat → SIGTERM→SIGKILL, independent per-worker restart deadlines.
  A sentry thread turns supervisor events into rotation changes: a dead
  worker leaves rotation the moment its corpse is reaped, rejoins only
  after its respawn republishes a ready file (fresh pid) — and the
  respawned worker re-warms its own buckets before that, so it rejoins
  hot.
* **Real-SIGKILL failover** — a killed worker's in-flight batch surfaces
  as a typed :class:`~paddle_tpu.serving.worker.TransportError` inside
  the breaker machinery, and PR 15's exactly-once re-route sends it to a
  healthy peer (``serving.fleet.reroutes``) while the supervisor respawns
  the corpse. The idempotency tokens are the router's request ids, so
  at-most-twice execution still holds under genuine process death.
* **Queue-depth routing** — dispatch picks the CLOSED replica with the
  fewest in-flight batches (half-open probes keep absolute priority so
  recovery happens under traffic), and ``max_concurrency`` tells the
  Endpoint to run that many dispatch threads, which is what makes N
  processes N-fold goodput instead of a serialized curiosity.
* **Elastic capacity** — :meth:`try_scale_out` spawns one more worker
  (to ``max_replicas``), :meth:`scale_in` drains one (to
  ``min_replicas``); :class:`FleetAutoscaler` drives both from Watcher
  findings and is mounted as the brownout ladder's FIRST rung, so
  sustained SLO breach adds capacity before any request is shed.

``close()`` tears the whole pod down — drain, shutdown messages,
supervisor SIGTERM→SIGKILL sweep — and is what the "zero orphan
processes" CI assertion holds to account.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

from ..errors import ExecutionTimeoutError, InvalidArgumentError, \
    UnavailableError
from .replica import CLOSED, HALF_OPEN, OPEN, ReplicaSet, _Replica
from .worker import TransportError, recv_msg, send_msg

__all__ = ["FleetAutoscaler", "ProcessReplicaSet"]


def _typed_remote_error(etype, msg):
    """Rehydrate a worker-side error by taxonomy name; unknown names
    degrade to UnavailableError (still typed, still retryable-ish)."""
    from .. import errors as _errors

    cls = getattr(_errors, etype, None)
    if isinstance(cls, type) and issubclass(cls, Exception):
        return cls(msg)
    return UnavailableError(f"worker error {etype}: {msg}")


class _WorkerClient:
    """Runner-surface client for one worker process.

    The contract (feed/fetch names, per-sample specs) comes from the
    worker's ready file, not from loading the model — the parent never
    holds the executable. ``call`` runs under a per-client lock (one
    in-order request/reply stream per worker); replies are matched by id
    and stale ids (a straggler from an abandoned attempt) are discarded
    (``serving.fleet.stale_replies``) instead of desynchronizing the
    stream. Socket-level failures close the connection and surface
    typed: OS errors / torn frames as :class:`TransportError`
    (UnavailableError → breaker + failover), timeouts as
    :class:`ExecutionTimeoutError`.
    """

    def __init__(self, name, ready, io_timeout=None, connect_timeout=5.0):
        self.name = name
        self.inflight = 0
        self._io_timeout = io_timeout
        self._connect_timeout = float(connect_timeout)
        self._lock = threading.Lock()
        self._sock = None
        self._seq = itertools.count(1)
        self._bind(ready, first=True)

    def _bind(self, ready, first=False):
        """Adopt a (re)published ready contract: host/port/pid of the
        current incarnation. On rebind the old socket is dropped."""
        import numpy as np

        self.pid = int(ready["pid"])
        self.host = ready["host"]
        self.port = int(ready["port"])
        self.attempt = int(ready.get("attempt", 0))
        feed = tuple(ready["feed_names"])
        fetch = tuple(ready["fetch_names"])
        specs = {
            n: (tuple(shape), np.dtype(dt))
            for n, (shape, dt) in ready["sample_specs"].items()
        }
        if first:
            self.feed_names, self.fetch_names = feed, fetch
            self._specs = specs
        elif feed != self.feed_names or fetch != self.fetch_names:
            raise InvalidArgumentError(
                f"worker {self.name!r} respawned with a different "
                f"contract: feeds {feed} fetches {fetch}"
            )
        with self._lock:
            self._drop_socket()

    def rebind(self, ready):
        self._bind(ready, first=False)

    # -- runner surface ----------------------------------------------------
    def sample_spec(self, name):
        return self._specs[name]

    def run(self, feed):
        reply = self.call("run", {"feed": feed})
        if reply["kind"] == "error":
            raise _typed_remote_error(reply["etype"], reply["msg"])
        return reply["outs"]

    # -- wire --------------------------------------------------------------
    def _drop_socket(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connected(self):
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self._connect_timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self._io_timeout)
            self._sock = s
        return self._sock

    def call(self, kind, payload=None, timeout=None):
        """One request/reply exchange; returns the reply dict."""
        from .. import observability as _obs

        mid = f"{self.name}:{next(self._seq)}"
        msg = {"kind": kind, "id": mid}
        if payload:
            msg.update(payload)
        with self._lock:
            try:
                sock = self._connected()
                if timeout is not None:
                    sock.settimeout(timeout)
                send_msg(sock, msg)
                while True:
                    reply = recv_msg(sock)
                    if reply is None:
                        raise TransportError(
                            f"worker {self.name!r} (pid {self.pid}) "
                            "closed the connection mid-call"
                        )
                    if reply.get("id") != mid:
                        # straggler from an attempt the watchdog already
                        # abandoned: recognized by id, dropped, stream
                        # stays usable
                        _obs.add("serving.fleet.stale_replies")
                        continue
                    return reply
            except socket.timeout as exc:
                # a timed-out read may sit mid-frame: the stream is no
                # longer framed-aligned, so the connection is burned
                self._drop_socket()
                raise ExecutionTimeoutError(
                    f"worker {self.name!r} (pid {self.pid}) exceeded "
                    f"its reply timeout"
                ) from exc
            except TransportError:
                self._drop_socket()
                raise
            except OSError as exc:
                self._drop_socket()
                raise TransportError(
                    f"worker {self.name!r} (pid {self.pid}) transport "
                    f"failed: {exc}"
                ) from exc
            finally:
                if timeout is not None and self._sock is not None:
                    self._sock.settimeout(self._io_timeout)

    def shutdown(self, timeout=5.0):
        """Ask the worker to exit cleanly (the scale-in path)."""
        try:
            reply = self.call("shutdown", timeout=timeout)
            return reply.get("kind") == "bye"
        except Exception:
            return False

    def close(self):
        with self._lock:
            self._drop_socket()


class ProcessReplicaSet(ReplicaSet):
    """N process-isolated workers behind the ReplicaSet runner surface.

    ``model_dir`` is a ``FrozenModel.save`` export; each worker loads it
    into its own process. The set plugs straight into
    ``Server.add_endpoint`` — ``max_concurrency`` additionally tells the
    Endpoint to dispatch that many batches in parallel.
    """

    def __init__(self, model_dir, n_workers=2, *, max_replicas=None,
                 min_replicas=1, warm_buckets=(), breaker_threshold=2,
                 cooldown_s=2.0, attempt_timeout=10.0,
                 heartbeat_timeout=10.0, max_restarts=3,
                 restart_backoff=0.25, restart_backoff_cap=5.0,
                 spawn_timeout=60.0, workdir=None, name="fleet",
                 host="127.0.0.1", env=None, python=None,
                 publish_dir=None, publish_mode="follow",
                 publish_poll=0.5):
        from .. import observability as _obs
        from ..resilience.health import PREEMPTION_EXIT_CODE, \
            heartbeat_path
        from ..resilience.supervisor import Supervisor

        if int(n_workers) < 1:
            raise InvalidArgumentError(
                f"ProcessReplicaSet needs >= 1 worker, got {n_workers}"
            )
        self.model_dir = os.fspath(model_dir)
        self.n_workers = int(n_workers)
        self.max_replicas = int(max_replicas or n_workers)
        self.min_replicas = max(1, int(min_replicas))
        self.warm_buckets = tuple(int(b) for b in warm_buckets)
        if publish_mode not in ("follow", "managed"):
            raise InvalidArgumentError(
                f"publish_mode must be 'follow' or 'managed', got "
                f"{publish_mode!r}"
            )
        self.publish_dir = (
            None if publish_dir is None else os.fspath(publish_dir)
        )
        self.publish_mode = publish_mode
        self.publish_poll = float(publish_poll)
        self.spawn_timeout = float(spawn_timeout)
        self.host = host
        self._python = python or sys.executable
        self._extra_env = dict(env or {})
        self._preemption_rc = PREEMPTION_EXIT_CODE
        if workdir is None:
            workdir = tempfile.mkdtemp(prefix="paddle-fleet-")
        self.workdir = workdir
        self._hb_dir = os.path.join(workdir, "hb")
        self._telemetry_dir = os.path.join(workdir, "telemetry")
        self._log_dir = os.path.join(workdir, "logs")
        for d in (self._hb_dir, self._telemetry_dir, self._log_dir):
            os.makedirs(d, exist_ok=True)

        self._next_rank = 0
        self._ranks = {}        # worker name -> rank (hb shard id)
        self._clients = {}      # worker name -> _WorkerClient
        self._pending = {}      # worker name -> (proc, deadline) awaiting ready
        self._sup_lock = threading.Lock()
        # io timeout: the attempt watchdog types the caller-side timeout;
        # the socket deadline just frees the dispatch thread shortly after
        io_timeout = (
            None if attempt_timeout is None else float(attempt_timeout) + 2.0
        )
        self._io_timeout = io_timeout
        self._sup = Supervisor(
            spawn=self._spawn_worker,
            max_restarts=max_restarts,
            backoff_base=restart_backoff,
            backoff_cap=restart_backoff_cap,
            staleness=self._beat_staleness,
            stale_after=float(heartbeat_timeout) * 2.0,
            clean_exit=lambda rc, hung: not hung and rc in (
                0, PREEMPTION_EXIT_CODE
            ),
        )

        names = [self._new_name() for _ in range(self.n_workers)]
        with self._sup_lock:
            for wname in names:
                self._sup.add(wname)
                _obs.add("serving.fleet.spawns")
        for wname in names:
            ready = self._wait_ready(
                wname, self._proc(wname), self.spawn_timeout
            )
            self._clients[wname] = _WorkerClient(
                wname, ready, io_timeout=io_timeout
            )

        super().__init__(
            dict(self._clients),
            breaker_threshold=breaker_threshold,
            cooldown_s=cooldown_s,
            attempt_timeout=attempt_timeout,
            heartbeats={
                n: heartbeat_path(self._hb_dir, self._ranks[n])
                for n in self._clients
            },
            heartbeat_timeout=heartbeat_timeout,
            name=name,
        )

        # the chaos CI asserts these names EXIST even at zero — a run
        # with no deaths must still prove the counters are wired
        for c in ("spawns", "respawns", "reroutes", "worker_deaths",
                  "scale_outs", "scale_ins"):
            _obs.add(f"serving.fleet.{c}", 0)
        _obs.set_gauge("serving.fleet.size", float(self.n_workers))

        self.first_scale_out_state = None
        self._stop = threading.Event()
        self._sentry = threading.Thread(
            target=self._sentry_loop, daemon=True,
            name=f"fleet-sentry-{name}",
        )
        self._sentry.start()

    # endpoints read this to size their dispatch pool: dispatching
    # serially to N processes would serialize them right back
    @property
    def max_concurrency(self):
        return self.max_replicas

    # -- spawning ----------------------------------------------------------
    def _new_name(self):
        rank = self._next_rank
        self._next_rank += 1
        wname = f"w{rank}"
        self._ranks[wname] = rank
        return wname

    def _ready_path(self, wname):
        return os.path.join(self.workdir, f"ready_{wname}.json")

    def _spawn_worker(self, wname, attempt):
        """Supervisor spawn hook: build one worker process."""
        rank = self._ranks[wname]
        ready = self._ready_path(wname)
        # a stale ready file from the previous incarnation must never be
        # mistaken for the respawn's readiness: pid match guards it, but
        # removing it up front makes the wait unambiguous
        try:
            os.unlink(ready)
        except OSError:
            pass
        cmd = [
            self._python, "-m", "paddle_tpu.serving.worker",
            "--model-dir", self.model_dir,
            "--ready-file", ready,
            "--host", self.host,
            "--name", wname,
            "--attempt", str(attempt),
        ]
        if self.warm_buckets:
            cmd += [
                "--warm-buckets",
                ",".join(str(b) for b in self.warm_buckets),
            ]
        if self.publish_dir:
            # the worker catches up to the last committed version before
            # readiness, so a respawn after a mid-apply SIGKILL rejoins
            # consistent with no parent involvement
            cmd += [
                "--publish-dir", self.publish_dir,
                "--publish-mode", self.publish_mode,
                "--publish-poll", str(self.publish_poll),
            ]
        env = dict(os.environ)
        env.update(self._extra_env)
        env["PADDLE_HEARTBEAT_DIR"] = self._hb_dir
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TPU_TELEMETRY_DIR"] = self._telemetry_dir
        log = open(
            os.path.join(self._log_dir, f"{wname}.log"), "ab"
        )
        proc = subprocess.Popen(
            cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        proc._paddle_rank = rank
        proc._paddle_log = log
        return proc

    def _proc(self, wname):
        with self._sup_lock:
            return self._sup.proc(wname)

    def _beat_staleness(self, proc, now_wall):
        from ..resilience.health import heartbeat_path, read_beat

        path = heartbeat_path(
            self._hb_dir, getattr(proc, "_paddle_rank", 0)
        )
        beat = read_beat(path)
        if beat and "time" in beat:
            stale = now_wall - float(beat["time"])
        else:
            stale = now_wall - getattr(proc, "_paddle_spawned", now_wall)
        return max(0.0, stale)

    def _read_ready(self, wname, proc):
        """The current incarnation's ready contract, or None. A pid
        mismatch is a stale file from a dead incarnation."""
        try:
            with open(self._ready_path(wname)) as f:
                ready = json.load(f)
        except (OSError, ValueError):
            return None
        if int(ready.get("pid", -1)) != proc.pid:
            return None
        return ready

    def _wait_ready(self, wname, proc, timeout):
        """Block until `wname` publishes readiness (initial spawn path)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready = self._read_ready(wname, proc)
            if ready is not None:
                return ready
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        rc = proc.poll()
        raise UnavailableError(
            f"fleet worker {wname!r} never became ready within "
            f"{timeout}s"
            + (f" (exited rc={rc})" if rc is not None else "")
            + f"; log: {os.path.join(self._log_dir, wname + '.log')}"
        )

    # -- routing: least-inflight over CLOSED, probes keep priority ---------
    def _pick(self, exclude):
        now = self._clock()
        with self._lock:
            closed, probe = [], None
            for rep in self._order:
                if rep.name in exclude or rep.draining:
                    continue
                if not self._beat_ok(rep):
                    continue
                if rep.state == CLOSED:
                    closed.append(rep)
                elif probe is None and (
                        (rep.state == OPEN
                         and now - rep.opened_at >= self.cooldown_s)
                        or (rep.state == HALF_OPEN and not rep.probing)):
                    probe = rep
            if probe is not None:
                probe.state = HALF_OPEN
                probe.probing = True
                self._gauge(probe)
                return probe
            if closed:
                lo = min(r.runner.inflight for r in closed)
                cands = [r for r in closed if r.runner.inflight == lo]
                self._rr += 1
                return cands[self._rr % len(cands)]
            return None

    def _dispatch(self, rep):
        inner = super()._dispatch(rep)
        client = rep.runner

        def attempt(feed):
            with self._lock:
                client.inflight += 1
            try:
                return inner(feed)
            finally:
                with self._lock:
                    client.inflight -= 1

        return attempt

    def _note_failover(self, n):
        from .. import observability as _obs

        _obs.add("serving.fleet.reroutes")

    # -- sentry: supervisor events -> rotation membership ------------------
    def _sentry_loop(self):
        while not self._stop.wait(0.2):
            try:
                with self._sup_lock:
                    events = self._sup.poll()
                for ev in events:
                    self._on_event(ev)
                self._poll_pending()
            except Exception:  # the sentry must outlive any one tick
                if self._stop.is_set():
                    return

    def _on_event(self, ev):
        from .. import observability as _obs

        wname, kind = ev["key"], ev["kind"]
        if kind == "hung":
            _obs.add("serving.fleet.hung_workers")
        elif kind == "restart_scheduled":
            # worker died: out of rotation NOW (the breaker would get
            # there after threshold failures; the supervisor knows
            # sooner), in-flight batches fail over via the normal path
            _obs.add("serving.fleet.worker_deaths")
            self._set_draining(wname, True)
            client = self._clients.get(wname)
            if client is not None:
                client.close()
        elif kind == "respawned":
            self._pending[wname] = (
                ev["proc"], time.monotonic() + self.spawn_timeout
            )
        elif kind in ("fatal", "exit_clean"):
            # fatal: restart budget exhausted — the worker stays out.
            # exit_clean outside scale-in (which forgets first): same.
            if kind == "fatal":
                _obs.add("serving.fleet.dead_ends")
            self._set_draining(wname, True)
            client = self._clients.get(wname)
            if client is not None:
                client.close()
        self._publish_size()

    def _poll_pending(self):
        """Promote respawned workers whose fresh ready file landed."""
        from .. import observability as _obs

        for wname in list(self._pending):
            proc, deadline = self._pending[wname]
            ready = self._read_ready(wname, proc)
            if ready is not None:
                try:
                    self._clients[wname].rebind(ready)
                except InvalidArgumentError:
                    del self._pending[wname]
                    continue
                del self._pending[wname]
                self.restore_replica(wname)
                _obs.add("serving.fleet.respawns")
                self._publish_size()
            elif time.monotonic() > deadline or proc.poll() is not None:
                # let the supervisor's own poll route the death; just
                # stop waiting on this incarnation
                if proc.poll() is None:
                    del self._pending[wname]

    def _set_draining(self, wname, flag):
        try:
            rep = self._find(wname)
        except InvalidArgumentError:
            return
        with self._lock:
            rep.draining = bool(flag)

    def _publish_size(self):
        from .. import observability as _obs

        _obs.set_gauge("serving.fleet.size", float(self.healthy_count()))

    # -- elastic capacity --------------------------------------------------
    def healthy_count(self):
        with self._lock:
            return sum(1 for rep in self._order if not rep.draining)

    def worker_pids(self):
        """Live worker pids (the orphan-check surface for tests/CI)."""
        with self._sup_lock:
            return [p.pid for p in self._sup.live_procs()]

    # -- live publish plane ------------------------------------------------
    def apply_update(self, wname, version=None, timeout=30.0):
        """Tell one worker to apply a published model version (None =
        newest eligible); the worker serializes the apply against its
        batch loop, so this is fence-safe by construction. Returns the
        ``applied`` reply dict; worker-side failures raise typed."""
        reply = self._clients[wname].call(
            "apply_update", {"version": version}, timeout=timeout
        )
        if reply.get("kind") == "error":
            raise _typed_remote_error(reply["etype"], reply["msg"])
        return reply

    def worker_digest(self, wname, timeout=30.0):
        """One worker's per-persistable CRC32 digest — the cross-process
        bitwise-equality probe CI compares against a cold chain fold."""
        reply = self._clients[wname].call("digest", timeout=timeout)
        if reply.get("kind") == "error":
            raise _typed_remote_error(reply["etype"], reply["msg"])
        return reply

    def try_scale_out(self):
        """Spawn one more worker (async: it enters rotation when ready).
        False when already at ``max_replicas``. The FIRST scale-out
        snapshots ``serving.shed`` so the chaos leg can prove capacity
        was added before any shedding."""
        from .. import observability as _obs

        with self._lock:
            active = sum(1 for rep in self._order if not rep.draining)
            pending = len(self._pending)
        if active + pending >= self.max_replicas:
            return False
        wname = self._new_name()
        with self._sup_lock:
            proc = self._sup.add(wname)
        _obs.add("serving.fleet.spawns")
        if self.first_scale_out_state is None:
            counters = _obs.get_counters()
            self.first_scale_out_state = {
                "shed": counters.get("serving.shed", 0),
                "time": time.time(),
            }
        # placeholder replica, draining until its ready file lands —
        # the sentry's pending machinery flips it live
        from ..resilience.health import heartbeat_path

        beat_path = heartbeat_path(self._hb_dir, self._ranks[wname])
        client = _WorkerClient.__new__(_WorkerClient)
        client.name = wname
        client.inflight = 0
        client._io_timeout = self._io_timeout
        client._connect_timeout = 5.0
        client._lock = threading.Lock()
        client._sock = None
        client._seq = itertools.count(1)
        client.feed_names = self.feed_names
        client.fetch_names = self.fetch_names
        client._specs = {
            n: self.sample_spec(n) for n in self.feed_names
        }
        client.pid = -1
        client.host, client.port, client.attempt = self.host, -1, 0
        self._clients[wname] = client
        rep = _Replica(wname, client, beat_path)
        rep.draining = True
        with self._lock:
            self._order.append(rep)
            self._gauge(rep)
        self._pending[wname] = (
            proc, time.monotonic() + self.spawn_timeout
        )
        _obs.add("serving.fleet.scale_outs")
        return True

    def scale_in(self):
        """Drain one worker (clean shutdown, supervision forgotten).
        False at the ``min_replicas`` floor. Prefers the idlest live
        worker, latest-spawned on ties."""
        from .. import observability as _obs

        with self._lock:
            live = [rep for rep in self._order if not rep.draining]
            if len(live) <= self.min_replicas:
                return False
            victim = min(
                reversed(live), key=lambda r: r.runner.inflight
            )
            victim.draining = True
        with self._sup_lock:
            proc = self._sup.forget(victim.name)
        client = self._clients.get(victim.name)
        if client is not None:
            client.shutdown()
            client.close()
        if proc is not None:
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            log = getattr(proc, "_paddle_log", None)
            if log is not None:
                log.close()
        _obs.add("serving.fleet.scale_ins")
        self._publish_size()
        return True

    # -- teardown ----------------------------------------------------------
    def drain(self, timeout=None):
        """Endpoint-drain hook: nothing queued fleet-side; the router owns
        the queues. Present so Server.drain treats the runner uniformly."""
        return True

    def close(self, grace=5.0):
        """Full teardown: stop the sentry, ask every worker to exit,
        then SIGTERM→SIGKILL the stragglers. Leaves zero orphans."""
        from .. import observability as _obs

        self._stop.set()
        self._sentry.join(timeout=5.0)
        with self._lock:
            for rep in self._order:
                rep.draining = True
        for client in self._clients.values():
            client.shutdown(timeout=1.0)
            client.close()
        with self._sup_lock:
            self._sup.terminate(grace=grace)
        _obs.add("serving.fleet.closes")
        _obs.set_gauge("serving.fleet.size", 0.0)


class FleetAutoscaler:
    """Findings → fleet size. The brownout ladder's first rung.

    ``observe(breach)`` is called once per control tick (the
    BrownoutController's poll cadence). ``breach_after`` consecutive
    breach ticks scale OUT (capacity before shedding); ``idle_after``
    consecutive idle ticks — no breach AND zero new requests, measured
    as a ``serving.requests`` counter delta — scale IN. ``cooldown_s``
    separates consecutive actions so one sustained breach adds workers
    one at a time, watching each addition land.
    """

    def __init__(self, fleet, breach_after=2, idle_after=10,
                 cooldown_s=15.0, clock=time.monotonic):
        self.fleet = fleet
        self.breach_after = int(breach_after)
        self.idle_after = int(idle_after)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._breach_streak = 0
        self._idle_streak = 0
        self._last_action_at = None
        self._last_requests = None

    def _requests_idle(self):
        from .. import observability as _obs

        cur = _obs.get_counters().get("serving.requests", 0)
        prev, self._last_requests = self._last_requests, cur
        return prev is not None and cur == prev

    def observe(self, breach, idle=None):
        """One control tick. Returns "scale_out", "scale_in", or None."""
        if idle is None:
            idle = (not breach) and self._requests_idle()
        elif breach:
            idle = False
        self._breach_streak = self._breach_streak + 1 if breach else 0
        self._idle_streak = self._idle_streak + 1 if idle else 0
        now = self._clock()
        if (self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s):
            return None
        if self._breach_streak >= self.breach_after:
            if self.fleet.try_scale_out():
                self._breach_streak = 0
                self._last_action_at = now
                return "scale_out"
            return None
        if self._idle_streak >= self.idle_after:
            if self.fleet.scale_in():
                self._idle_streak = 0
                self._last_action_at = now
                return "scale_in"
        return None
