"""Circuit-broken replica failover: N frozen replicas behind one endpoint.

The r8 router binds an endpoint to ONE runner: a wedged or crashing
executable takes the whole endpoint (and, under SIGTERM, the whole
``Server.drain``) down with it. :class:`ReplicaSet` is the serving-side
analog of the training stack's elastic restart — it IS a runner (same
``feed_names``/``sample_spec``/``run`` surface), so an
:class:`serving.router.Endpoint` fronts N replicas without the router
changing, and it adds the fault domain the single-runner path cannot
have:

* **Per-replica circuit breakers** — ``breaker_threshold`` consecutive
  dispatch failures open a replica's breaker (``serving.breaker_state.
  <replica>`` gauge: 0 closed / 0.5 half-open / 1 open). An open breaker
  takes the replica out of rotation; after ``cooldown_s`` the next batch
  is routed to it as a HALF-OPEN probe (driven through the
  ``resilience/retry.py`` policy machinery) — success closes the
  breaker, failure re-opens it. A probe is real traffic, so its batch is
  protected by the exactly-once re-route below.
* **Bounded dispatch** — ``attempt_timeout`` runs each replica dispatch
  under the retry policy's watchdog thread: a HUNG executable (the
  ``serving.dispatch:hang`` chaos kind) surfaces as a typed
  ``ExecutionTimeoutError`` after the timeout instead of wedging the
  scheduler forever, and counts as a breaker failure.
* **Exactly-once failover** — a failed dispatch re-routes its batch to a
  healthy replica ONCE (``serving.requeued`` counts the requests,
  ``serving.failovers`` the batches), keyed on the router's idempotent
  per-request ids: a request that already survived one re-route is never
  re-routed again (at-most-twice execution, bounded by construction),
  the failure surfaces typed instead.
* **Heartbeat-informed health** — pass ``heartbeats={name: beat_path}``
  (the PR-3 ``Heartbeat`` file contract) and a replica whose beat is
  staler than ``heartbeat_timeout`` is treated as unhealthy before a
  single dispatch is burned on it.
* **Per-replica drain** — :meth:`drain_replica` takes one replica out of
  rotation (and drains its runner if it has a ``drain``) while the set
  keeps serving; :meth:`restore_replica` re-admits it with a reset
  breaker (the replaced-replica story).

The ``serving.dispatch`` fault seam fires INSIDE each replica attempt
(plus a per-replica ``serving.dispatch.<name>`` seam for targeted
chaos), i.e. under the breaker/timeout machinery — injected raise/hang
kinds exercise exactly the failover path production failures take.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..errors import InvalidArgumentError, UnavailableError

__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "ReplicaSet"]

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}


class _Replica:
    __slots__ = ("name", "runner", "state", "consecutive_failures",
                 "opened_at", "draining", "probing", "beat_path",
                 "beat_ok", "beat_checked_at")

    def __init__(self, name, runner, beat_path=None):
        self.name = name
        self.runner = runner
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.draining = False
        self.probing = False
        self.beat_path = beat_path
        # cached heartbeat verdict (re-read at a bounded rate, not per
        # dispatch: _pick holds the routing lock)
        self.beat_ok = True
        self.beat_checked_at = None


class ReplicaSet:
    """Front N runner replicas with circuit breakers + 1x failover.

    ``replicas`` is ``{name: runner}`` (or a list, named ``r0..rN-1``);
    every replica must expose the same ``feed_names`` (the FrozenRunner
    surface). The set itself is a runner, so plug it straight into
    ``Server.add_endpoint(name, replica_set, config)``.
    """

    # the router hands us the batch's request ids (idempotency tokens for
    # exactly-once re-routing) and leaves the dispatch fault seam to us
    wants_request_ids = True

    def __init__(self, replicas, breaker_threshold=3, cooldown_s=2.0,
                 attempt_timeout=None, heartbeats=None,
                 heartbeat_timeout=10.0, name="replicas",
                 clock=time.monotonic):
        from ..resilience.retry import retry

        if not isinstance(replicas, dict):
            replicas = {f"r{i}": r for i, r in enumerate(replicas)}
        if not replicas:
            raise InvalidArgumentError("ReplicaSet needs >= 1 replica")
        if int(breaker_threshold) < 1:
            raise InvalidArgumentError(
                f"breaker_threshold must be >= 1, got {breaker_threshold}"
            )
        heartbeats = heartbeats or {}
        self.name = name
        self.breaker_threshold = int(breaker_threshold)
        self.cooldown_s = float(cooldown_s)
        self.attempt_timeout = (
            None if attempt_timeout is None else float(attempt_timeout)
        )
        self.heartbeat_timeout = float(heartbeat_timeout)
        self._clock = clock
        self._order = [
            _Replica(n, r, heartbeats.get(n)) for n, r in replicas.items()
        ]
        first = self._order[0].runner
        self.feed_names = tuple(first.feed_names)
        self.fetch_names = tuple(getattr(first, "fetch_names", ()))
        for rep in self._order[1:]:
            if tuple(rep.runner.feed_names) != self.feed_names:
                raise InvalidArgumentError(
                    f"replica {rep.name!r} feed_names "
                    f"{tuple(rep.runner.feed_names)} != {self.feed_names}"
                )
            # fetch order IS the output contract: a replica frozen with a
            # different fetch set would silently serve wrong-attributed
            # rows after a failover
            fetches = tuple(getattr(rep.runner, "fetch_names", ()))
            if fetches != self.fetch_names:
                raise InvalidArgumentError(
                    f"replica {rep.name!r} fetch_names {fetches} != "
                    f"{self.fetch_names}"
                )
        self._lock = threading.Lock()
        # bucket feeds remembered from warmup_run, keyed by batch size:
        # restore_replica(rewarm=True) replays them on JUST the restored
        # replica so a shape-changing live update re-compiles outside any
        # measured request
        self._warm_feeds = {}
        # round-robin cursor over the healthy set; starts so the FIRST
        # dispatch lands on the first declared replica (deterministic)
        self._rr = -1
        # ids that already consumed their one re-route (bounded memory:
        # ids are monotonic, so evicting the oldest is safe)
        self._rerouted = set()
        self._rerouted_fifo = deque()
        self._rerouted_cap = 65536
        # max_attempts=1: the retry POLICY only contributes the watchdog
        # thread bounding one attempt — re-routing (this class) is the
        # retry, and it must land on a DIFFERENT replica
        self._attempt_policy = retry(
            max_attempts=1, attempt_timeout=self.attempt_timeout,
            name="serving.dispatch",
        )
        for rep in self._order:
            self._gauge(rep)

    # -- runner surface ----------------------------------------------------
    def sample_spec(self, name):
        return self._order[0].runner.sample_spec(name)

    def validate_config(self, config):
        for rep in self._order:
            validate = getattr(rep.runner, "validate_config", None)
            if validate is not None:
                validate(config)

    def warmup_run(self, feed):
        """Warm EVERY (non-draining) replica on this bucket feed, breaker
        and fault seam bypassed: a standby that compiles during failover
        would pay the cold-start exactly when latency matters most.
        Returns the last replica's outputs (the warmup discards them)."""
        if feed:
            # remember one feed per bucket size so a restored replica
            # can be re-warmed alone (restore_replica(rewarm=True))
            batch = len(next(iter(feed.values())))
            with self._lock:
                self._warm_feeds[int(batch)] = feed
        out = None
        for rep in self._order:
            if not rep.draining:
                out = rep.runner.run(feed)
        if out is None:
            raise UnavailableError(
                f"replica set {self.name!r}: every replica is draining"
            )
        return out

    # -- dispatch + failover -----------------------------------------------
    def run(self, feed, request_ids=None):
        """Dispatch one batch: route to a healthy replica; on failure,
        re-route to another healthy replica EXACTLY once (idempotent
        request ids), then surface the failure typed."""
        from .. import observability as _obs

        # requeued counts REQUESTS: the ids when the router provided
        # them (a partial batch is padded, so feed rows overcount)
        n = (len(request_ids) if request_ids
             else len(next(iter(feed.values()))) if feed else 0)
        tried = []
        rep = self._pick(tried)
        if rep is None:
            raise UnavailableError(
                f"replica set {self.name!r}: no healthy replica "
                f"(states {self.states()})"
            )
        for hop in (0, 1):
            try:
                out = self._dispatch(rep)
                out = out(feed)
            except Exception as exc:
                self._on_failure(rep, exc)
                tried.append(rep.name)
                if hop == 1:
                    raise
                # the one re-route: only counted (and only charged
                # against the requests' idempotency tokens) once a
                # healthy failover TARGET actually exists
                rep = self._pick(tried)
                if rep is None:
                    raise
                if not self._mark_rerouted(request_ids):
                    # some request in this batch already consumed its
                    # one re-route on an earlier call: refuse a second
                    # (unbounded duplicate execution), surface the
                    # failure instead
                    raise
                _obs.add("serving.failovers")
                _obs.add("serving.requeued", n)
                _obs.add(f"serving.requeued.{self.name}", n)
                self._note_failover(n)
                continue
            self._on_success(rep)
            _obs.add(f"serving.replica_dispatches.{rep.name}")
            return out

    def _note_failover(self, n):
        """Extension point: a subclass records its own failover metric
        (the process fleet counts ``serving.fleet.reroutes`` here)."""

    def _dispatch(self, rep):
        from ..resilience.faults import fault_point

        def attempt(feed):
            # the dispatch chaos seams, INSIDE the watchdog-bounded
            # attempt: a raising kind reads as a replica failure, a hang
            # as a wedged executable the timeout converts to a typed
            # ExecutionTimeoutError
            fault_point("serving.dispatch")
            fault_point(f"serving.dispatch.{rep.name}")
            return rep.runner.run(feed)

        if self.attempt_timeout is None:
            return attempt
        return lambda feed: self._attempt_policy.call(attempt, feed)

    # -- breaker core ------------------------------------------------------
    def _gauge(self, rep):
        from .. import observability as _obs

        _obs.set_gauge(
            f"serving.breaker_state.{rep.name}", _STATE_GAUGE[rep.state]
        )

    def _beat_ok(self, rep):
        if rep.beat_path is None:
            return True
        # the verdict is cached for a fraction of the staleness budget:
        # one beat-file read per recheck window, not one per dispatch
        # (this runs under the routing lock on the hot path)
        now = time.time()
        recheck = min(1.0, self.heartbeat_timeout / 4.0)
        if (rep.beat_checked_at is not None
                and now - rep.beat_checked_at < recheck):
            return rep.beat_ok
        from ..resilience.health import read_beat

        beat = read_beat(rep.beat_path)
        rep.beat_checked_at = now
        rep.beat_ok = bool(
            beat and "time" in beat
            and now - float(beat["time"]) <= self.heartbeat_timeout
        )
        return rep.beat_ok

    def _pick(self, exclude):
        """Choose the dispatch target: a due half-open probe first (so
        recovery actually happens under traffic — a failed probe re-routes
        safely), else round-robin over closed replicas."""
        now = self._clock()
        with self._lock:
            closed, probe = [], None
            for rep in self._order:
                if rep.name in exclude or rep.draining:
                    continue
                if not self._beat_ok(rep):
                    continue
                if rep.state == CLOSED:
                    closed.append(rep)
                elif probe is None and (
                        (rep.state == OPEN
                         and now - rep.opened_at >= self.cooldown_s)
                        or (rep.state == HALF_OPEN and not rep.probing)):
                    probe = rep
            if probe is not None:
                probe.state = HALF_OPEN
                probe.probing = True
                self._gauge(probe)
                return probe
            if closed:
                self._rr += 1
                return closed[self._rr % len(closed)]
            return None

    def _on_success(self, rep):
        from .. import observability as _obs

        with self._lock:
            was = rep.state
            rep.state = CLOSED
            rep.consecutive_failures = 0
            rep.probing = False
            self._gauge(rep)
        if was != CLOSED:
            _obs.add("serving.breaker_closed")
            _obs.add(f"serving.breaker_closed.{rep.name}")

    def _on_failure(self, rep, exc):
        from .. import observability as _obs

        _obs.add("serving.dispatch_failures")
        _obs.add(f"serving.dispatch_failures.{rep.name}")
        opened = False
        with self._lock:
            rep.consecutive_failures += 1
            was_probe = rep.probing
            rep.probing = False
            if (was_probe or rep.state == HALF_OPEN
                    or rep.consecutive_failures >= self.breaker_threshold):
                opened = rep.state != OPEN
                rep.state = OPEN
                rep.opened_at = self._clock()
                self._gauge(rep)
        if opened:
            _obs.add("serving.breaker_opened")
            _obs.add(f"serving.breaker_opened.{rep.name}")
            from ..observability import recorder as _recorder

            # flight-recorder trigger: the window holding the failures
            # that opened the breaker is the post-mortem for "why did
            # replica X get ejected"
            _recorder.flight_dump("breaker_open", detail={
                "replica": rep.name, "set": self.name,
                "consecutive_failures": rep.consecutive_failures,
                "error": f"{type(exc).__name__}: {exc}",
            })

    def _mark_rerouted(self, request_ids):
        """Claim the one re-route for every id in the batch; False when
        any id already spent its re-route (callers must surface the
        failure instead of re-routing again)."""
        if not request_ids:
            return True
        with self._lock:
            if any(rid in self._rerouted for rid in request_ids):
                return False
            for rid in request_ids:
                self._rerouted.add(rid)
                self._rerouted_fifo.append(rid)
            while len(self._rerouted_fifo) > self._rerouted_cap:
                self._rerouted.discard(self._rerouted_fifo.popleft())
        return True

    # -- introspection / lifecycle -----------------------------------------
    def states(self):
        """{replica: breaker state} snapshot ('draining' overrides)."""
        with self._lock:
            return {
                rep.name: ("draining" if rep.draining else rep.state)
                for rep in self._order
            }

    def _find(self, name):
        for rep in self._order:
            if rep.name == name:
                return rep
        raise InvalidArgumentError(
            f"no replica {name!r} in set {self.name!r} "
            f"({[r.name for r in self._order]})"
        )

    def drain_replica(self, name, timeout=None):
        """Take one replica out of rotation (per-replica SIGTERM drain):
        the set keeps serving on the survivors. Drains the replica's own
        runner when it has a ``drain``. Returns the runner's drain result
        (or True)."""
        from .. import observability as _obs

        rep = self._find(name)
        with self._lock:
            rep.draining = True
        _obs.add("serving.replica_drains")
        _obs.set_gauge(f"serving.replica_draining.{name}", 1.0)
        drain = getattr(rep.runner, "drain", None)
        return drain(timeout) if drain is not None else True

    def restore_replica(self, name, rewarm=False):
        """Re-admit a drained (or broken) replica with a reset breaker —
        the replaced-replica path. With ``rewarm=True`` the feeds
        remembered from :meth:`warmup_run` are replayed on JUST this
        replica first (while it is still out of rotation), so a live
        update that changed persistable shapes — a grown hot tier, say —
        pays its re-compiles here instead of inside a measured request.
        (Without remembered feeds, or for a cold new runner, the caller
        falls back to a full ``Endpoint.warmup()``.)"""
        from .. import observability as _obs

        rep = self._find(name)
        if rewarm:
            with self._lock:
                feeds = list(self._warm_feeds.values())
            for feed in feeds:
                try:
                    rep.runner.run(feed)
                except Exception:
                    # a failed re-warm is a latency problem, not an
                    # admission problem: the replica still restores and
                    # the breaker machinery owns real dispatch failures
                    break
            if feeds:
                _obs.add("serving.replica_rewarms")
        with self._lock:
            rep.draining = False
            rep.state = CLOSED
            rep.consecutive_failures = 0
            rep.probing = False
            self._gauge(rep)
        _obs.set_gauge(f"serving.replica_draining.{name}", 0.0)
