"""Adaptive brownout: watcher findings -> graceful degradation ladder.

PR 13's :class:`observability.watch.Watcher` raises ``slo_breach`` /
``step_regression`` findings that nothing consumed — an overloaded
server kept batching at full patience until the hard ``max_queue`` cliff.
:class:`BrownoutController` closes that loop: sustained breach signal
walks a degradation LADDER (each rung applied to every endpoint via
:meth:`Endpoint.apply_brownout`), and sustained recovery walks it back
down — graceful degradation instead of cliff-edge rejection:

======  ==========================================================
rung    behavior
======  ==========================================================
0       full service (the configured knobs)
1       halve the batch-former max-wait (latency over fill)
2       quarter the max-wait AND shed the BACKGROUND class at
        admission (``RequestShedError``)
3       also shed the BATCH class (interactive-only service)
4       additionally cap the bucket set to its lower half — the
        last-ditch latency-over-THROUGHPUT move: big buckets are
        the batching engine, so this rung cuts capacity and is
        only reached when shedding everything non-interactive
        still did not clear the SLO
======  ==========================================================

Rung ordering is load-bearing: shedding REDUCES demand while the bucket
cap reduces CAPACITY — capping before shedding (measured on the overload
bench) pins the saturated queue's wait at the deadline and mass-expires
the class the ladder is protecting.

The decision core is :meth:`observe` — pure state machine over (new
findings, current p99), deterministic and directly testable: a breach
signal on ``escalate_after`` consecutive observations steps UP one rung;
p99 at or under ``slo_p99_s * recover_margin`` for ``recover_after``
consecutive observations steps DOWN one (hysteresis both ways, so a
noisy p99 cannot flap the ladder). :meth:`poll` feeds it live — new
watcher findings plus the ``watch.request_p99_s`` gauge the watcher
maintains (or, with no watcher wired, a window p99 the controller
computes itself from the latency histogram's bucket deltas via the
shared ``observability.metrics.window_p99``) — and :meth:`start` wraps
poll in a daemon thread.

With an ``autoscaler=`` (a :class:`serving.fleet.FleetAutoscaler` over a
process fleet) the ladder gains a rung BEFORE rung 1: sustained breach
first ADDS capacity — spawn a worker, up to ``max_replicas`` — and an
observation the autoscaler absorbed with a scale-out resets the
escalation streak, so demand is never cut while the fleet can still
grow. Sustained idle walks the same rung the other way (drain a worker,
down to ``min_replicas``). Only a fleet at max size (or one breaching
through the autoscaler's cooldown) falls through to the degradation
rungs below.

Observability: ``serving.brownout_level`` gauge (plus the per-endpoint
``serving.brownout_level.<ep>`` the endpoints maintain),
``serving.brownout_escalations`` / ``serving.brownout_recoveries`` /
``serving.brownout_scale_outs`` counters.
"""

from __future__ import annotations

import threading

from ..errors import InvalidArgumentError
from .router import BACKGROUND, BATCH

__all__ = ["DEFAULT_LADDER", "BrownoutController"]

# rung -> Endpoint.apply_brownout kwargs; index 0 is full service.
# Demand-reducing rungs (shed) come BEFORE the capacity-reducing one
# (bucket cap) — see the module docstring. "freeze_publishes" is NOT an
# endpoint knob: the controller pops it and routes it to the live-publish
# plane (a ``publish_control`` with freeze/unfreeze — e.g.
# ``serving.rollout.RolloutController``), so a server already shedding
# interactive-adjacent traffic stops paying model-apply stalls and
# canary churn on top; recovery below the rung unfreezes.
DEFAULT_LADDER = (
    {"wait_scale": 1.0, "bucket_frac": 1.0, "shed_priority": None},
    {"wait_scale": 0.5, "bucket_frac": 1.0, "shed_priority": None},
    {"wait_scale": 0.25, "bucket_frac": 1.0, "shed_priority": BACKGROUND},
    {"wait_scale": 0.25, "bucket_frac": 1.0, "shed_priority": BATCH},
    {"wait_scale": 0.25, "bucket_frac": 0.5, "shed_priority": BATCH,
     "freeze_publishes": True},
)

_BREACH_KINDS = ("slo_breach", "step_regression")


class BrownoutController:
    """Consume watcher findings; drive the endpoints' brownout ladder."""

    def __init__(self, server, slo_p99_s=None, watcher=None,
                 ladder=DEFAULT_LADDER, escalate_after=2, recover_after=4,
                 recover_margin=0.8, interval=0.5, autoscaler=None,
                 publish_control=None):
        if len(ladder) < 2:
            raise InvalidArgumentError(
                "brownout ladder needs >= 2 rungs (rung 0 = full service)"
            )
        if not 0.0 < float(recover_margin) <= 1.0:
            raise InvalidArgumentError(
                f"recover_margin must be in (0, 1], got {recover_margin}"
            )
        self._server = server
        self.slo_p99_s = None if slo_p99_s is None else float(slo_p99_s)
        self.watcher = watcher
        self.ladder = tuple(ladder)
        self.escalate_after = int(escalate_after)
        self.recover_after = int(recover_after)
        self.recover_margin = float(recover_margin)
        self.interval = float(interval)
        self.autoscaler = autoscaler
        self.publish_control = publish_control
        self.latency_metric = "serving.request_latency"
        self.level = 0
        self._breach_obs = 0
        self._ok_obs = 0
        self._lat_prev = None  # cumulative buckets at the last fallback poll
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._apply()

    # -- decision core -----------------------------------------------------
    def observe(self, findings=(), p99=None):
        """One observation of the breach signal; returns the (possibly
        changed) ladder level. `findings` are watcher finding dicts (only
        ``slo_breach``/``step_regression`` kinds count); `p99` is the
        current window p99 in seconds (compared against ``slo_p99_s``
        for sustained-breach detection and for recovery — the watcher
        only LATCHES one finding per excursion, so escalation past rung 1
        needs the level signal, not just edges)."""
        breach = any(
            f.get("kind") in _BREACH_KINDS for f in findings or ()
        )
        ok = False
        if p99 is not None and self.slo_p99_s is not None:
            if p99 > self.slo_p99_s:
                breach = True
            elif p99 <= self.slo_p99_s * self.recover_margin:
                ok = not breach
        # the ladder's rung-zero: capacity BEFORE degradation. A breach
        # tick the autoscaler absorbs with a scale-out resets the
        # escalation streak — demand is never cut while the fleet can
        # still grow; only at max_replicas (or breaching through the
        # autoscaler's cooldown) does the ladder trade service away.
        action = None
        if self.autoscaler is not None:
            try:
                action = self.autoscaler.observe(breach)
            except Exception:
                action = None
            if action == "scale_out":
                from .. import observability as _obs

                _obs.add("serving.brownout_scale_outs")
        with self._lock:
            if breach:
                self._breach_obs += 1
                self._ok_obs = 0
            elif ok:
                self._ok_obs += 1
                self._breach_obs = 0
            elif p99 is not None:
                # dead band (recovered-ish but above the re-arm margin):
                # BOTH streaks reset — two transient breaches hours apart
                # must not add up to an escalation, and sub-margin blips
                # interleaved with near-SLO hovering must not add up to a
                # recovery. A no-signal observation (p99 None, no
                # findings) leaves both streaks untouched.
                self._breach_obs = 0
                self._ok_obs = 0
            if action == "scale_out":
                self._breach_obs = 0
            changed = None
            if (breach and self._breach_obs >= self.escalate_after
                    and self.level < len(self.ladder) - 1):
                self.level += 1
                self._breach_obs = 0
                changed = "serving.brownout_escalations"
            elif (ok and self._ok_obs >= self.recover_after
                    and self.level > 0):
                self.level -= 1
                self._ok_obs = 0
                changed = "serving.brownout_recoveries"
            level = self.level
        if changed is not None:
            from .. import observability as _obs

            _obs.add(changed)
            self._apply()
        return level

    def _apply(self):
        from .. import observability as _obs

        rung = dict(self.ladder[self.level])
        # the publish-freeze rung key is consumed here, never forwarded:
        # Endpoint.apply_brownout owns latency knobs only
        freeze = bool(rung.pop("freeze_publishes", False))
        if self.publish_control is not None:
            try:
                if freeze:
                    self.publish_control.freeze()
                else:
                    self.publish_control.unfreeze()
            except Exception:
                pass  # degraded publishing must not break degradation
        endpoints = getattr(self._server, "endpoints", None)
        eps = (
            list(endpoints().values()) if callable(endpoints)
            else list(self._server)
        )
        for ep in eps:
            ep.apply_brownout(level=self.level, **rung)
        _obs.set_gauge("serving.brownout_level", float(self.level))

    # -- live wiring -------------------------------------------------------
    def poll(self):
        """One live observation: drain the watcher's new findings (when
        one is attached) and read its p99 gauge. The current rung is
        re-applied every poll (idempotent), so an endpoint registered
        AFTER an escalation picks up the active brownout within one
        interval instead of serving at full patience through the
        breach."""
        from ..observability import metrics

        findings = self.watcher.poll() if self.watcher is not None else ()
        p99 = metrics.get_gauges().get("watch.request_p99_s")
        if p99 is None and self.watcher is None:
            p99 = self._window_p99()
        level = self.observe(findings, p99)
        self._apply()
        return level

    def _window_p99(self):
        """Watcher-less fallback: compute the window p99 directly from
        the latency histogram's bucket deltas with the same shared
        ``metrics.window_p99`` the watcher uses — a controller deployed
        without a watcher degrades on the identical signal instead of
        flying blind until someone wires one up."""
        from ..observability import metrics

        h = metrics.get_histograms().get(self.latency_metric)
        if h is None:
            return None
        prev, self._lat_prev = self._lat_prev, h["buckets"]
        return metrics.window_p99(prev, h["buckets"])

    def start(self):
        """Poll on a daemon thread every ``interval`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serving-brownout"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception:
                pass  # a broken poll must not kill the controller thread
