"""Request router with continuous/dynamic batching over bucketed shapes.

One request = one sample (feed arrays WITHOUT the leading batch axis).
Requests are admitted into per-endpoint, per-priority-class queues; a
scheduler thread forms batches continuously: it waits until either enough
requests queue to fill the largest bucket or the OLDEST queued request
hits the max-wait deadline, then pads the batch up to the nearest
configured bucket and runs it as ONE program dispatch. Because every
batch lands on a bucket shape with the endpoint's exact fetch set, the
executor's per-(program, feed-shapes, fetch-set) executable LRU serves
every request after warmup with zero compiles — the serving analogue of
the PR-6 "one wide program" argument (arXiv:2301.13062: many small
per-request programs lose badly to one bucketed one).

Fault domain (r15) — the serving-side analog of the training stack's
elastic-restart/rollback story:

* **Deadline propagation** — ``submit(..., deadline_ms=)`` stamps the
  request with an absolute expiry. The scheduler drops already-expired
  requests BEFORE batch formation (their futures resolve with the typed
  ``errors.DeadlineExceededError``; ``serving.expired`` counters), and
  the batch-former's fill wait is clamped to the tightest surviving
  deadline, so a queued request is dispatched before it would expire and
  stale work never pads a bucket or burns a dispatch.
* **Priority classes + load shedding** — requests carry a priority class
  (``INTERACTIVE`` < ``BATCH`` < ``BACKGROUND``; lower value = more
  important). Batches form in strict priority order (FIFO within a
  class). When the queue is full, an arriving request evicts the
  youngest request of a strictly LOWER class instead of being rejected —
  the victim's future resolves with ``errors.RequestShedError``
  (``serving.shed`` counters) — and only when nothing lower-class is
  queued does the arrival itself get rejected (``serving.rejected``, the
  r8 behavior).
* **Brownout** — :meth:`Endpoint.apply_brownout` installs graceful-
  degradation knobs the :class:`serving.brownout.BrownoutController`
  ladder drives from watcher findings: a ``wait_scale`` shrinking the
  effective max-wait, a ``bucket_frac`` capping the bucket set (smaller
  batches dispatch sooner), and a ``shed_priority`` refusing whole
  priority classes at admission.
* **Goodput** — completions are split into ``serving.goodput``
  (resolved within their deadline; deadline-less requests count) vs
  ``serving.late_completions``, so "QPS" under overload means work
  somebody was still waiting for.

Replica failover lives in :mod:`serving.replica` — a
:class:`ReplicaSet` is just a runner, so an endpoint fronts N frozen
replicas with per-replica circuit breakers without the router changing.

Lifecycle: ``Server.drain()`` stops admission, flushes every in-flight
batch (expired requests still resolve with their typed error — a drain
never hangs on dead work), and stops the scheduler threads; the
remaining drain budget is PRO-RATED across endpoints so ``drain(t)``
takes ~t, not endpoints*t. :func:`install_preemption_handler` rides the
PR-3 SIGTERM/exit-75 contract.

Observability (PR-1 registry): ``serving.requests`` / ``.rejected`` /
``.requests_served`` / ``.request_errors`` / ``.expired`` / ``.shed`` /
``.goodput`` / ``.late_completions`` counters (+ per-endpoint and
per-class variants), ``serving.queue_depth`` / ``.brownout_level``
gauges, ``serving.batches`` counter, ``serving.batch_fill`` +
``serving.padding_waste`` histograms, ``serving.request_latency`` +
``serving.batch_latency`` histograms, ``serving.drained`` counter.

Fault seams: request ingestion passes ``fault_point("serving.ingest")``
under a retry policy; batch dispatch passes
``fault_point("serving.dispatch")`` (in :class:`ReplicaSet` the seam
fires per replica attempt under its breaker/timeout machinery; on a
plain endpoint a raising kind fails the batch with its typed error and a
``hang`` wedges the scheduler — the failure mode ReplicaSet exists to
bound).
"""

from __future__ import annotations

import contextlib
import itertools
import math
import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..errors import (
    DeadlineExceededError,
    InvalidArgumentError,
    PreconditionNotMetError,
    RequestShedError,
)

# batch-fill / padding-waste are ratios in [0, 1]; latency histograms use
# the registry's default latency edges
_RATIO_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# the batch-former wakes this far BEFORE the tightest queued deadline:
# waking exactly AT it would find the request already expired and drop
# work that one early dispatch would have served in-budget
_DEADLINE_MARGIN_S = 0.002

# priority classes: lower value = more important. Any non-negative int is
# accepted (the ladder sheds ">= shed_priority"), these three are the
# named contract.
INTERACTIVE = 0
BATCH = 1
BACKGROUND = 2

PRIORITY_NAMES = {INTERACTIVE: "interactive", BATCH: "batch",
                  BACKGROUND: "background"}

_RID = itertools.count(1)


def class_name(priority):
    """Metric label for a priority class (named, else the raw int)."""
    return PRIORITY_NAMES.get(priority, str(int(priority)))


class ServerDrainingError(PreconditionNotMetError):
    """Admission refused: the server is draining (SIGTERM) or stopped."""


class EndpointConfig:
    """Batching knobs for one endpoint.

    * ``buckets`` — allowed batch sizes, ascending; a formed batch pads up
      to the smallest bucket that fits (largest bucket caps batch size).
    * ``max_wait_ms`` — how long the OLDEST queued request may wait for
      co-batching before the scheduler dispatches a partial batch.
    * ``max_queue`` — admission bound; beyond it submits first try to
      evict a lower-priority queued request (``serving.shed``) and only
      then reject (``serving.rejected``), so an overloaded server
      degrades by shedding the least important work first.
    """

    def __init__(self, buckets=(1, 2, 4, 8), max_wait_ms=5.0,
                 max_queue=1024):
        sizes = sorted(int(b) for b in buckets)
        if not sizes or sizes[0] <= 0:
            raise InvalidArgumentError(
                f"endpoint buckets must be positive, got {sizes}"
            )
        self.buckets = tuple(sizes)
        self.max_wait = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)


class _Request:
    __slots__ = ("feeds", "future", "t_enqueue", "ctx", "deadline",
                 "priority", "rid")

    def __init__(self, feeds, deadline_s=None, priority=INTERACTIVE):
        self.feeds = feeds
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        # absolute expiry on the same clock as t_enqueue; None = patient
        self.deadline = (
            None if deadline_s is None else self.t_enqueue + deadline_s
        )
        self.priority = int(priority)
        # idempotency token for failover: a ReplicaSet re-routes a failed
        # batch's requests to a healthy replica EXACTLY once, keyed on
        # these ids
        self.rid = next(_RID)
        # TraceContext parenting this request's scheduler-side spans
        # (queue wait, dispatch) under its ingest span — the explicit
        # capture/activate handoff across the scheduler thread boundary
        self.ctx = None


class FrozenRunner:
    """Default runner: a FrozenModel executed through an Executor/Scope.

    Feed variables must be declared batch-leading (shape[0] == -1 or the
    sample rank excludes the batch axis); fetches must be per-sample
    tensors with the batch leading, the same contract as
    ``AnalysisConfig.set_batch_buckets``.
    """

    def __init__(self, frozen, executor=None, scope=None):
        from ..framework.executor import Executor
        from ..framework.scope import global_scope

        self.frozen = frozen
        self.executor = executor or Executor()
        self.scope = scope or global_scope()
        self.feed_names = tuple(frozen.feed_names)
        self.fetch_names = tuple(frozen.fetch_names)
        self._sample_specs = {}
        blk = frozen.program.global_block
        for n in self.feed_names:
            v = blk.var(n)
            shape = tuple(v.shape or ())
            if not shape or shape[0] not in (-1, None):
                raise InvalidArgumentError(
                    f"serving feed {n!r} must be declared batch-leading "
                    f"(-1 first dim), got {shape}"
                )
            self._sample_specs[n] = (tuple(shape[1:]), v.dtype)

    def sample_spec(self, name):
        """(per-sample shape, dtype) for feed `name`."""
        return self._sample_specs[name]

    def run(self, feed):
        """Run one padded bucket batch; returns batch-leading outputs."""
        return self.executor.run(
            self.frozen.program, feed=feed,
            fetch_list=list(self.fetch_names), scope=self.scope,
        )


class Endpoint:
    """One servable model: queue + scheduler thread + bucketed dispatch."""

    def __init__(self, name, runner, config=None):
        from .. import observability as _obs
        from ..resilience.retry import retry

        self.name = name
        self.runner = runner
        self.config = config or EndpointConfig()
        # runners with static shape constraints (e.g. the GPT generator's
        # compiled cache batch) veto incompatible bucket configs up front
        validate = getattr(runner, "validate_config", None)
        if validate is not None:
            validate(self.config)
        # per-priority-class FIFO deques; batches form in priority order
        self._queues: dict[int, deque] = {}
        # how many QUEUED requests carry a deadline: the expiry/clamp
        # helpers early-out on 0, so the deadline-less path (and any
        # deadline-less backlog) never pays per-wake full-queue scans
        self._deadline_count = 0
        self._cond = threading.Condition()
        # serializes runner.run between the scheduler thread and warmup():
        # stateful runners (the GPT generator's shared KV-cache scope)
        # must never see two interleaved dispatches
        self._run_lock = threading.Lock()
        self._draining = False
        self._stopped = False
        # brownout knobs (apply_brownout); read by admission + scheduler
        self._brownout_level = 0
        self._wait_scale = 1.0
        self._bucket_cap = None
        self._shed_priority = None
        self._obs = _obs
        self._ingest_retry = retry(
            max_attempts=3, base_delay=0.005, max_delay=0.1,
            name="serving.ingest",
        )
        # runners fronting N processes (ProcessReplicaSet) advertise
        # max_concurrency: that many dispatcher threads run batches in
        # parallel — serial dispatch would serialize N workers right
        # back into single-process throughput. The handoff queue is
        # maxsize=1 so the batch-former stages at most one batch ahead
        # (backpressure, not an unbounded buffer).
        self._concurrency = max(
            1, int(getattr(runner, "max_concurrency", 1) or 1)
        )
        self._dispatch_q = None
        self._dispatchers = []
        if self._concurrency > 1:
            self._dispatch_q = _queue.Queue(maxsize=1)
            for i in range(self._concurrency):
                t = threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name=f"serving-{name}-d{i}",
                )
                t.start()
                self._dispatchers.append(t)
        self._thread = threading.Thread(
            target=self._schedule_loop, daemon=True,
            name=f"serving-{name}",
        )
        self._thread.start()

    # -- queue helpers (call with self._cond held) -------------------------
    def _qsize_locked(self):
        return sum(len(q) for q in self._queues.values())

    def _gauge_depth_locked(self):
        self._obs.set_gauge(
            f"serving.queue_depth.{self.name}", self._qsize_locked()
        )

    def _oldest_enqueue_locked(self):
        return min(q[0].t_enqueue for q in self._queues.values() if q)

    def _tightest_deadline_locked(self):
        """Smallest absolute deadline among queued requests, or None.
        O(queued-with-deadlines) with an O(1) all-patient early-out; the
        queue itself is bounded by ``max_queue``."""
        if not self._deadline_count:
            return None
        tight = None
        for q in self._queues.values():
            for r in q:
                if r.deadline is not None and (
                        tight is None or r.deadline < tight):
                    tight = r.deadline
        return tight

    def _drop_expired_locked(self, now=None):
        """Remove every queued request whose deadline has passed; the
        caller resolves them (with the cond lock RELEASED — a future's
        done-callbacks may re-enter submit)."""
        if not self._deadline_count:
            return []
        now = time.perf_counter() if now is None else now
        expired = []
        for p, q in self._queues.items():
            if any(r.deadline is not None and now > r.deadline for r in q):
                keep = deque()
                for r in q:
                    if r.deadline is not None and now > r.deadline:
                        expired.append(r)
                    else:
                        keep.append(r)
                self._queues[p] = keep
        if expired:
            self._deadline_count -= len(expired)
            self._gauge_depth_locked()
        return expired

    def _evict_lower_locked(self, priority):
        """Pop the YOUNGEST request of the LOWEST class strictly below
        `priority`'s importance (highest class value), or None."""
        victim_class = None
        for p, q in self._queues.items():
            if p > priority and q and (victim_class is None
                                       or p > victim_class):
                victim_class = p
        if victim_class is None:
            return None
        victim = self._queues[victim_class].pop()
        if victim.deadline is not None:
            self._deadline_count -= 1
        return victim

    def _pop_batch_locked(self, n):
        batch = []
        for p in sorted(self._queues):
            q = self._queues[p]
            while q and len(batch) < n:
                batch.append(q.popleft())
            if len(batch) >= n:
                break
        self._deadline_count -= sum(
            1 for r in batch if r.deadline is not None
        )
        return batch

    def _effective_buckets(self):
        cap = self._bucket_cap
        if cap is None:
            return self.config.buckets
        capped = tuple(b for b in self.config.buckets if b <= cap)
        return capped or (self.config.buckets[0],)

    # -- expiry / shed resolution (lock NOT held) --------------------------
    def _resolve_expired(self, expired):
        from ..observability import spans

        now = time.perf_counter()
        for r in expired:
            self._obs.add("serving.expired")
            self._obs.add(f"serving.expired.{self.name}")
            self._obs.add(f"serving.expired_class.{class_name(r.priority)}")
            spans.record(
                "serving.expired", now - r.t_enqueue, category="serving",
                ctx=r.ctx, args={"endpoint": self.name},
            )
            r.future.set_exception(DeadlineExceededError(
                f"request expired in {self.name!r} queue after "
                f"{now - r.t_enqueue:.3f}s (deadline "
                f"{r.deadline - r.t_enqueue:.3f}s); never dispatched"
            ))

    def _count_shed(self, req):
        self._obs.add("serving.shed")
        self._obs.add(f"serving.shed.{self.name}")
        self._obs.add(f"serving.shed_class.{class_name(req.priority)}")

    # -- brownout ----------------------------------------------------------
    def apply_brownout(self, level=0, wait_scale=1.0, bucket_frac=1.0,
                       shed_priority=None):
        """Install one rung of the brownout ladder: scale the effective
        max-wait, cap the bucket set to its lowest ``bucket_frac``
        fraction, and refuse admission for classes ``>= shed_priority``.
        ``apply_brownout()`` with no args restores full service."""
        if wait_scale <= 0 or not 0.0 < bucket_frac <= 1.0:
            raise InvalidArgumentError(
                f"brownout wants wait_scale > 0 and 0 < bucket_frac <= 1, "
                f"got {wait_scale}/{bucket_frac}"
            )
        buckets = self.config.buckets
        cap = (None if bucket_frac >= 1.0 else
               buckets[max(0, math.ceil(len(buckets) * bucket_frac) - 1)])
        with self._cond:
            self._brownout_level = int(level)
            self._wait_scale = float(wait_scale)
            self._bucket_cap = cap
            self._shed_priority = (
                None if shed_priority is None else int(shed_priority)
            )
            self._cond.notify_all()
        self._obs.set_gauge(
            f"serving.brownout_level.{self.name}", float(level)
        )

    @property
    def brownout_level(self):
        return self._brownout_level

    # -- admission ---------------------------------------------------------
    def submit(self, feeds, deadline_ms=None, priority=INTERACTIVE):
        """Admit one single-sample request; returns its Future.

        ``deadline_ms`` is the client's end-to-end latency budget: once it
        elapses the scheduler drops the request pre-dispatch and the
        future raises ``DeadlineExceededError``. ``priority`` is the
        request's class (``INTERACTIVE``/``BATCH``/``BACKGROUND`` or any
        non-negative int; lower = more important) — under pressure the
        lowest class sheds first (``RequestShedError``)."""
        if deadline_ms is not None and float(deadline_ms) <= 0:
            raise InvalidArgumentError(
                f"deadline_ms must be positive, got {deadline_ms}"
            )
        if int(priority) < 0:
            raise InvalidArgumentError(
                f"priority class must be >= 0, got {priority}"
            )
        deadline_s = None if deadline_ms is None else float(deadline_ms) / 1e3
        try:
            return self._ingest_retry.call(
                self._ingest, feeds, deadline_s, int(priority)
            )
        except ServerDrainingError:
            self._obs.add("serving.rejected")
            self._obs.add(f"serving.rejected.{self.name}")
            raise

    def _ingest(self, feeds, deadline_s, priority):
        from ..observability import trace
        from ..resilience.faults import fault_point

        # the chaos seam (dataloader.fetch analogue): an armed fault
        # raises HERE, before any state mutation, so the retry re-admits
        # the identical request with no double-enqueue hazard
        fault_point("serving.ingest")
        feeds = {
            n: np.asarray(feeds[n]) for n in self.runner.feed_names
        }
        req = _Request(feeds, deadline_s, priority)
        evicted = None
        # each request gets a causal trace: join the submitter's active
        # trace when there is one (the client's own span becomes the
        # root), else start a fresh one — either way the scheduler-side
        # spans parent under THIS ingest span via the request's context
        tr = trace.ensure()
        try:
            with trace.activate(tr), \
                    self._obs.span("serving.ingest", category="serving",
                                   endpoint=self.name) as ingest_span:
                with self._cond:
                    if self._draining or self._stopped:
                        raise ServerDrainingError(
                            f"endpoint {self.name!r} is draining; request "
                            "refused"
                        )
                    shed_at = self._shed_priority
                    if shed_at is not None and req.priority >= shed_at:
                        self._count_shed(req)
                        raise RequestShedError(
                            f"endpoint {self.name!r} browned out (level "
                            f"{self._brownout_level}): class "
                            f"{class_name(req.priority)!r} is shed"
                        )
                    if self._qsize_locked() >= self.config.max_queue:
                        evicted = self._evict_lower_locked(req.priority)
                        if evicted is None:
                            self._obs.add("serving.rejected")
                            self._obs.add(f"serving.rejected.{self.name}")
                            raise PreconditionNotMetError(
                                f"endpoint {self.name!r} queue full "
                                f"({self.config.max_queue}) with nothing "
                                "lower-priority to shed; back off or add "
                                "capacity"
                            )
                    if tr is not None and ingest_span.span_id is not None:
                        req.ctx = tr.child(ingest_span.span_id)
                    self._queues.setdefault(req.priority, deque()).append(
                        req
                    )
                    if req.deadline is not None:
                        self._deadline_count += 1
                    self._gauge_depth_locked()
                    self._cond.notify_all()
        finally:
            # resolve the victim with the cond lock released: future
            # done-callbacks run inline and may re-enter submit
            if evicted is not None:
                self._count_shed(evicted)
                evicted.future.set_exception(RequestShedError(
                    f"request shed from {self.name!r}: queue full and a "
                    f"class-{class_name(priority)!r} admission outranked "
                    f"class {class_name(evicted.priority)!r}"
                ))
        self._obs.add("serving.requests")
        self._obs.add(f"serving.requests.{self.name}")
        return req.future

    # -- scheduling --------------------------------------------------------
    def _schedule_loop(self):
        while True:
            expired = []
            batch = None
            with self._cond:
                while not self._qsize_locked() and not self._stopped:
                    self._cond.wait(0.05)
                if self._stopped and not self._qsize_locked():
                    break
                # already-expired requests leave BEFORE batch formation:
                # late work never pads a bucket or burns a dispatch
                expired.extend(self._drop_expired_locked())
                if self._qsize_locked():
                    max_bucket = self._effective_buckets()[-1]
                    # continuous batching: admit late arrivals until the
                    # largest bucket fills, the oldest request's max-wait
                    # expires, or the TIGHTEST surviving deadline is
                    # reached (draining flushes immediately)
                    while (self._qsize_locked() < max_bucket
                           and not self._draining and not self._stopped):
                        wait_deadline = (
                            self._oldest_enqueue_locked()
                            + self.config.max_wait * self._wait_scale
                        )
                        tight = self._tightest_deadline_locked()
                        if tight is not None:
                            wait_deadline = min(
                                wait_deadline, tight - _DEADLINE_MARGIN_S
                            )
                        remaining = wait_deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                        expired.extend(self._drop_expired_locked())
                        if not self._qsize_locked():
                            break
                        max_bucket = self._effective_buckets()[-1]
                    batch = self._pop_batch_locked(
                        min(self._qsize_locked(), max_bucket)
                    )
                    # the bucket is chosen under the SAME lock hold that
                    # formed the batch: a concurrent brownout bucket-cap
                    # change must not shrink the target below the batch
                    # already popped
                    bucket = (
                        self._bucket_for_locked(len(batch)) if batch
                        else None
                    )
                    self._gauge_depth_locked()
            self._resolve_expired(expired)
            if batch:
                if self._dispatch_q is not None:
                    self._dispatch_q.put((batch, bucket))
                else:
                    self._run_batch(batch, bucket)
        # drain path: every staged batch runs before the scheduler
        # thread exits — Server.drain joins THIS thread, so "drained"
        # still means every admitted request resolved
        if self._dispatch_q is not None:
            for _ in self._dispatchers:
                self._dispatch_q.put(None)
            for t in self._dispatchers:
                t.join()

    def _dispatch_loop(self):
        """One dispatcher: runs staged batches until the sentinel."""
        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            self._run_batch(*item)

    def _bucket_for_locked(self, n):
        buckets = self._effective_buckets()
        for b in buckets:
            if b >= n:
                return b
        return buckets[-1]

    def _run_batch(self, batch, bucket):
        from ..observability import spans, trace
        from ..resilience.faults import fault_point

        t0 = time.perf_counter()
        n = len(batch)
        # queue wait ends the moment the batch forms: recorded per
        # request under ITS trace (the capture/activate handoff — this
        # runs on the scheduler thread, the context was captured at
        # ingest), so "where did this request's latency go" splits into
        # queue-wait vs dispatch from the trace alone
        for r in batch:
            spans.record(
                "serving.queue_wait", t0 - r.t_enqueue,
                category="serving", ctx=r.ctx,
                args={"endpoint": self.name, "batch_size": n},
            )
        try:
            feed = {}
            for name in self.runner.feed_names:
                rows = np.stack([r.feeds[name] for r in batch])
                if n < bucket:
                    pad = np.zeros(
                        (bucket - n,) + rows.shape[1:], rows.dtype
                    )
                    rows = np.concatenate([rows, pad], axis=0)
                feed[name] = rows
            # concurrent dispatchers skip the run lock: a runner that
            # declared max_concurrency > 1 (the process fleet) is
            # thread-safe by contract, and serializing here would undo it
            guard = (
                contextlib.nullcontext() if self._concurrency > 1
                else self._run_lock
            )
            with guard:
                # the live dispatch span (and everything the runner
                # records inside: executor.step, GPT prefill/decode)
                # files under the FIRST request's trace; the other
                # requests get their dispatch share recorded
                # retrospectively below, so every trace is complete
                with trace.activate(batch[0].ctx), \
                        self._obs.span("serving.batch", category="serving",
                                       endpoint=self.name, bucket=bucket,
                                       batch_size=n):
                    if getattr(self.runner, "wants_request_ids", False):
                        # failover runners (ReplicaSet) key exactly-once
                        # re-routing on the request ids; they own the
                        # serving.dispatch fault seam per replica attempt
                        outs = self.runner.run(
                            feed, request_ids=[r.rid for r in batch]
                        )
                    else:
                        fault_point("serving.dispatch")
                        outs = self.runner.run(feed)
                    outs = [np.asarray(o) for o in outs]
        except Exception as exc:
            self._obs.add("serving.request_errors", n)
            for r in batch:
                r.future.set_exception(exc)
            return
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        for r in batch:
            spans.record(
                "serving.dispatch", now - t0, category="serving",
                ctx=r.ctx,
                args={"endpoint": self.name, "bucket": bucket,
                      "batch_size": n},
            )
        self._obs.add("serving.batches")
        self._obs.add(f"serving.batches.{self.name}")
        self._obs.add(f"serving.bucket_runs.{self.name}.{bucket}")
        self._obs.observe("serving.batch_latency", dt)
        self._obs.observe(
            "serving.batch_fill", n / bucket, buckets=_RATIO_BUCKETS
        )
        self._obs.observe(
            "serving.padding_waste", (bucket - n) / bucket,
            buckets=_RATIO_BUCKETS,
        )
        self._obs.add("serving.padded_rows", bucket - n)
        goodput = late = 0
        for i, r in enumerate(batch):
            r.future.set_result([o[i] for o in outs])
            lat = now - r.t_enqueue
            if r.deadline is None or now <= r.deadline:
                goodput += 1
            else:
                late += 1
            self._obs.observe("serving.request_latency", lat)
            self._obs.observe(f"serving.request_latency.{self.name}", lat)
        self._obs.add("serving.requests_served", n)
        # goodput = completions somebody was still waiting for: the
        # in-deadline share (deadline-less requests count — their client
        # is patient by declaration)
        if goodput:
            self._obs.add("serving.goodput", goodput)
            self._obs.add(f"serving.goodput.{self.name}", goodput)
        if late:
            self._obs.add("serving.late_completions", late)
            self._obs.add(f"serving.late_completions.{self.name}", late)

    # -- warmup ------------------------------------------------------------
    def warmup(self):
        """Compile the EXACT (bucket-shape, fetch-set) executables serving
        will dispatch: one zero-feed run per bucket through the same
        ``runner.run`` entry the scheduler uses. The executor's executable
        cache (and its flops/estimate digests) key on the fetch set, so a
        warmup with a different fetch list — or a different batch shape —
        would leave every real bucket cold and push the first compile into
        a user-visible request latency (the PR-6 bench warmup lesson).
        A ReplicaSet exposes ``warmup_run``, which warms EVERY replica —
        a cold standby would otherwise pay its compiles during a
        failover, exactly when latency matters most.

        When ``PADDLE_TPU_HBM_BYTES`` is set and the runner exposes its
        frozen program, the static HBM plan for every (bucket, fetch-set)
        executable is validated FIRST — resident state once plus the
        worst bucket's transient peak times the runner's concurrency must
        fit the budget, or warmup refuses with a typed error *before*
        compiling anything (the concurrency-planning math the paged KV
        cache consumes)."""
        from ..core.dtypes import to_numpy_dtype

        self.plan_memory()
        run = getattr(self.runner, "warmup_run", None) or self.runner.run
        for b in self.config.buckets:
            feed = {}
            for name in self.runner.feed_names:
                shape, dtype = self.runner.sample_spec(name)
                feed[name] = np.zeros((b,) + shape, to_numpy_dtype(dtype))
            with self._run_lock:
                run(feed)
            self._obs.add("serving.warmup_runs")
        return len(self.config.buckets)

    def plan_memory(self, budget=None):
        """Static per-bucket HBM plan for this endpoint: resident bytes
        once + max-over-buckets (feeds + transient peak) × concurrency.
        Returns the plan dict (None when the runner exposes no program),
        publishes ``serving.warmup_peak_bytes.<endpoint>``, and raises
        :class:`~paddle_tpu.errors.PreconditionNotMetError` when a budget
        (argument, else ``PADDLE_TPU_HBM_BYTES``) is exceeded."""
        from ..analysis.memory import (
            _fmt_bytes, hbm_budget, plan_memory,
        )

        frozen = getattr(self.runner, "frozen", None)
        program = getattr(frozen, "program", None)
        if program is None:
            return None
        if budget is None:
            budget = hbm_budget()
        fetch_names = tuple(getattr(self.runner, "fetch_names", ()) or ())
        feed_names = tuple(getattr(self.runner, "feed_names", ()) or ())
        resident = 0.0
        per_bucket = {}
        worst = 0.0
        for b in self.config.buckets:
            feed_shapes = {}
            for name in feed_names:
                shape, _dtype = self.runner.sample_spec(name)
                feed_shapes[name] = (b,) + tuple(shape)
            mt = plan_memory(
                program, feed_names=feed_names, fetch_names=fetch_names,
                feed_shapes=feed_shapes, budget=None,
            )
            resident = max(resident, mt.resident_bytes)
            dynamic = mt.feed_bytes + mt.transient_peak_bytes
            per_bucket[b] = dynamic
            worst = max(worst, dynamic)
        planned = resident + worst * self._concurrency
        self._obs.set_gauge(
            f"serving.warmup_peak_bytes.{self.name}", planned
        )
        plan = {
            "resident_bytes": resident,
            "per_bucket_dynamic_bytes": per_bucket,
            "concurrency": self._concurrency,
            "planned_peak_bytes": planned,
            "budget_bytes": budget,
        }
        if budget is not None and planned > budget:
            from ..errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                f"endpoint {self.name!r} cannot fit the HBM budget: "
                f"resident {_fmt_bytes(resident)} + worst bucket "
                f"{_fmt_bytes(worst)} x concurrency {self._concurrency} "
                f"= {_fmt_bytes(planned)} > "
                f"{_fmt_bytes(budget)} (PADDLE_TPU_HBM_BYTES); shrink "
                "the buckets, the cache, or the replica concurrency"
            )
        return plan

    # -- lifecycle ---------------------------------------------------------
    def pending(self):
        with self._cond:
            return self._qsize_locked()

    def drain(self, timeout=None):
        """Stop admitting, flush the queue through the scheduler, stop the
        thread. Returns True when everything in flight completed (expired
        requests resolve with their typed error during the flush — dead
        work cannot hang a drain)."""
        with self._cond:
            self._draining = True
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout)
        return not self._thread.is_alive() and not self.pending()


class Server:
    """A set of endpoints behind one admission/drain lifecycle."""

    def __init__(self):
        self._endpoints = {}
        self._draining = False
        self._drained = threading.Event()
        self._lock = threading.Lock()

    def add_endpoint(self, name, runner, config=None, frozen=None,
                     executor=None, scope=None):
        """Register (and start) an endpoint. Pass a ``runner`` with the
        FrozenRunner interface, or ``frozen=`` to wrap a FrozenModel."""
        if frozen is not None:
            runner = FrozenRunner(frozen, executor=executor, scope=scope)
        if runner is None:
            raise InvalidArgumentError(
                "add_endpoint needs runner= or frozen="
            )
        with self._lock:
            if self._draining:
                raise ServerDrainingError("server is draining")
            if name in self._endpoints:
                raise InvalidArgumentError(
                    f"endpoint {name!r} already registered"
                )
            ep = Endpoint(name, runner, config)
            self._endpoints[name] = ep
        return ep

    def __getitem__(self, name):
        return self._endpoints[name]

    def endpoints(self):
        return dict(self._endpoints)

    def submit(self, endpoint, feeds, deadline_ms=None,
               priority=INTERACTIVE):
        if self._draining:
            from .. import observability as _obs

            _obs.add("serving.rejected")
            raise ServerDrainingError("server is draining")
        return self._endpoints[endpoint].submit(
            feeds, deadline_ms=deadline_ms, priority=priority
        )

    def warmup(self):
        """Warm every endpoint's bucket executables; returns total runs."""
        return sum(ep.warmup() for ep in self._endpoints.values())

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=None):
        """Graceful shutdown: stop admission, complete every admitted
        request, stop scheduler threads, then bump ``serving.drained``.
        Idempotent; returns True when fully drained. The budget is
        pro-rated: `timeout` bounds the WHOLE drain — each endpoint gets
        the remaining slice, not a fresh full timeout (the r8 bug: N
        endpoints with one wedged dispatch each could stall a SIGTERM
        for N*timeout)."""
        from .. import observability as _obs

        with self._lock:
            first = not self._draining
            self._draining = True
            eps = list(self._endpoints.values())
        deadline = None if timeout is None else time.monotonic() + timeout
        ok = True
        for ep in eps:
            remaining = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            ok = ep.drain(remaining) and ok
        if first:
            _obs.add("serving.drained")
            _obs.set_gauge("serving.draining", 1.0)
            from ..observability import recorder as _recorder

            # flight-recorder trigger: a drain usually precedes exit(75)
            # — capture the serving window while the process still can
            _recorder.flight_dump("serving_drain", detail={
                "endpoints": [ep.name for ep in eps], "clean": ok,
            })
        if ok:
            self._drained.set()
        return ok

    def wait_drained(self, timeout=None):
        return self._drained.wait(timeout)

    def close(self, timeout=None):
        """Drain, then release runner-held resources: every runner
        exposing ``close`` (the process fleet's worker pod) is torn
        down. Zero orphan worker processes after this call is the
        contract the fleet-chaos CI stage asserts."""
        from .. import observability as _obs

        ok = self.drain(timeout)
        for ep in self._endpoints.values():
            close = getattr(ep.runner, "close", None)
            if close is not None:
                close()
        _obs.add("serving.server_closes")
        return ok


def install_preemption_handler(server, exit_on_drain=True, timeout=None):
    """SIGTERM -> drain -> exit ``PREEMPTION_EXIT_CODE`` (75), riding the
    PR-3 preemption contract: the launcher treats 75 as a clean preempt
    (no restart-budget burn). The signal handler only spawns the drain
    thread (handlers must stay tiny); with ``exit_on_drain=False`` the
    caller observes ``server.wait_drained()`` instead — the in-process
    test shape."""
    import os
    import signal

    from ..resilience.health import PREEMPTION_EXIT_CODE

    def _drain_then_exit():
        server.drain(timeout)
        if exit_on_drain:
            # handlers/threads cannot sys.exit the main thread; preemption
            # wants no further cleanup anyway (checkpointless server)
            os._exit(PREEMPTION_EXIT_CODE)

    def _on_sigterm(signum, frame):
        threading.Thread(
            target=_drain_then_exit, daemon=True,
            name="serving-drain",
        ).start()

    old = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _on_sigterm)
    return old
