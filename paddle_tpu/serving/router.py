"""Request router with continuous/dynamic batching over bucketed shapes.

One request = one sample (feed arrays WITHOUT the leading batch axis).
Requests are admitted into a per-endpoint queue; a scheduler thread forms
batches continuously: it waits until either enough requests queue to fill
the largest bucket or the OLDEST queued request hits the max-wait
deadline, then pads the batch up to the nearest configured bucket and
runs it as ONE program dispatch. Because every batch lands on a bucket
shape with the endpoint's exact fetch set, the executor's
per-(program, feed-shapes, fetch-set) executable LRU serves every request
after warmup with zero compiles — the serving analogue of the PR-6
"one wide program" argument (arXiv:2301.13062: many small per-request
programs lose badly to one bucketed one).

Lifecycle: ``Server.drain()`` stops admission, flushes every in-flight
batch, and stops the scheduler threads; :func:`install_preemption_handler`
rides the PR-3 SIGTERM/exit-75 contract (drain, then exit
``PREEMPTION_EXIT_CODE`` — the launcher treats it as a clean preemption).

Observability (PR-1 registry): ``serving.requests`` / ``.rejected`` /
``.requests_served`` / ``.request_errors`` counters,
``serving.queue_depth`` gauge, ``serving.batches`` counter,
``serving.batch_fill`` + ``serving.padding_waste`` histograms,
``serving.request_latency`` + ``serving.batch_latency`` histograms (p50/
p99 come out of the bucket counts), ``serving.drained`` counter.

Fault seam: request ingestion passes ``fault_point("serving.ingest")``
under a retry policy — the dataloader.fetch-style chaos seam for the CI
serving smoke.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..errors import InvalidArgumentError, PreconditionNotMetError

# batch-fill / padding-waste are ratios in [0, 1]; latency histograms use
# the registry's default latency edges
_RATIO_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


class ServerDrainingError(PreconditionNotMetError):
    """Admission refused: the server is draining (SIGTERM) or stopped."""


class EndpointConfig:
    """Batching knobs for one endpoint.

    * ``buckets`` — allowed batch sizes, ascending; a formed batch pads up
      to the smallest bucket that fits (largest bucket caps batch size).
    * ``max_wait_ms`` — how long the OLDEST queued request may wait for
      co-batching before the scheduler dispatches a partial batch.
    * ``max_queue`` — admission bound; beyond it submits are rejected
      (``serving.rejected``) so an overloaded server degrades by shedding
      instead of growing an unbounded queue.
    """

    def __init__(self, buckets=(1, 2, 4, 8), max_wait_ms=5.0,
                 max_queue=1024):
        sizes = sorted(int(b) for b in buckets)
        if not sizes or sizes[0] <= 0:
            raise InvalidArgumentError(
                f"endpoint buckets must be positive, got {sizes}"
            )
        self.buckets = tuple(sizes)
        self.max_wait = float(max_wait_ms) / 1000.0
        self.max_queue = int(max_queue)


class _Request:
    __slots__ = ("feeds", "future", "t_enqueue", "ctx")

    def __init__(self, feeds):
        self.feeds = feeds
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        # TraceContext parenting this request's scheduler-side spans
        # (queue wait, dispatch) under its ingest span — the explicit
        # capture/activate handoff across the scheduler thread boundary
        self.ctx = None


class FrozenRunner:
    """Default runner: a FrozenModel executed through an Executor/Scope.

    Feed variables must be declared batch-leading (shape[0] == -1 or the
    sample rank excludes the batch axis); fetches must be per-sample
    tensors with the batch leading, the same contract as
    ``AnalysisConfig.set_batch_buckets``.
    """

    def __init__(self, frozen, executor=None, scope=None):
        from ..framework.executor import Executor
        from ..framework.scope import global_scope

        self.frozen = frozen
        self.executor = executor or Executor()
        self.scope = scope or global_scope()
        self.feed_names = tuple(frozen.feed_names)
        self.fetch_names = tuple(frozen.fetch_names)
        self._sample_specs = {}
        blk = frozen.program.global_block
        for n in self.feed_names:
            v = blk.var(n)
            shape = tuple(v.shape or ())
            if not shape or shape[0] not in (-1, None):
                raise InvalidArgumentError(
                    f"serving feed {n!r} must be declared batch-leading "
                    f"(-1 first dim), got {shape}"
                )
            self._sample_specs[n] = (tuple(shape[1:]), v.dtype)

    def sample_spec(self, name):
        """(per-sample shape, dtype) for feed `name`."""
        return self._sample_specs[name]

    def run(self, feed):
        """Run one padded bucket batch; returns batch-leading outputs."""
        return self.executor.run(
            self.frozen.program, feed=feed,
            fetch_list=list(self.fetch_names), scope=self.scope,
        )


class Endpoint:
    """One servable model: queue + scheduler thread + bucketed dispatch."""

    def __init__(self, name, runner, config=None):
        from .. import observability as _obs
        from ..resilience.retry import retry

        self.name = name
        self.runner = runner
        self.config = config or EndpointConfig()
        # runners with static shape constraints (e.g. the GPT generator's
        # compiled cache batch) veto incompatible bucket configs up front
        validate = getattr(runner, "validate_config", None)
        if validate is not None:
            validate(self.config)
        self._queue = deque()
        self._cond = threading.Condition()
        # serializes runner.run between the scheduler thread and warmup():
        # stateful runners (the GPT generator's shared KV-cache scope)
        # must never see two interleaved dispatches
        self._run_lock = threading.Lock()
        self._draining = False
        self._stopped = False
        self._obs = _obs
        self._ingest_retry = retry(
            max_attempts=3, base_delay=0.005, max_delay=0.1,
            name="serving.ingest",
        )
        self._thread = threading.Thread(
            target=self._schedule_loop, daemon=True,
            name=f"serving-{name}",
        )
        self._thread.start()

    # -- admission ---------------------------------------------------------
    def submit(self, feeds):
        """Admit one single-sample request; returns its Future."""
        try:
            return self._ingest_retry.call(self._ingest, feeds)
        except ServerDrainingError:
            self._obs.add("serving.rejected")
            self._obs.add(f"serving.rejected.{self.name}")
            raise

    def _ingest(self, feeds):
        from ..observability import trace
        from ..resilience.faults import fault_point

        # the chaos seam (dataloader.fetch analogue): an armed fault
        # raises HERE, before any state mutation, so the retry re-admits
        # the identical request with no double-enqueue hazard
        fault_point("serving.ingest")
        feeds = {
            n: np.asarray(feeds[n]) for n in self.runner.feed_names
        }
        req = _Request(feeds)
        # each request gets a causal trace: join the submitter's active
        # trace when there is one (the client's own span becomes the
        # root), else start a fresh one — either way the scheduler-side
        # spans parent under THIS ingest span via the request's context
        tr = trace.ensure()
        with trace.activate(tr), \
                self._obs.span("serving.ingest", category="serving",
                               endpoint=self.name) as ingest_span:
            with self._cond:
                if self._draining or self._stopped:
                    raise ServerDrainingError(
                        f"endpoint {self.name!r} is draining; request "
                        "refused"
                    )
                if len(self._queue) >= self.config.max_queue:
                    self._obs.add("serving.rejected")
                    self._obs.add(f"serving.rejected.{self.name}")
                    raise PreconditionNotMetError(
                        f"endpoint {self.name!r} queue full "
                        f"({self.config.max_queue}); shed load or add "
                        "capacity"
                    )
                if tr is not None and ingest_span.span_id is not None:
                    req.ctx = tr.child(ingest_span.span_id)
                self._queue.append(req)
                self._obs.set_gauge(
                    f"serving.queue_depth.{self.name}", len(self._queue)
                )
                self._cond.notify_all()
        self._obs.add("serving.requests")
        self._obs.add(f"serving.requests.{self.name}")
        return req.future

    # -- scheduling --------------------------------------------------------
    def _schedule_loop(self):
        max_bucket = self.config.buckets[-1]
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(0.05)
                if self._stopped and not self._queue:
                    return
                # continuous batching: admit late arrivals until the
                # largest bucket fills or the oldest request's deadline
                # expires (draining flushes immediately)
                deadline = self._queue[0].t_enqueue + self.config.max_wait
                while (len(self._queue) < max_bucket
                       and not self._draining and not self._stopped):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                n = min(len(self._queue), max_bucket)
                batch = [self._queue.popleft() for _ in range(n)]
                self._obs.set_gauge(
                    f"serving.queue_depth.{self.name}", len(self._queue)
                )
            if batch:
                self._run_batch(batch)

    def _bucket_for(self, n):
        for b in self.config.buckets:
            if b >= n:
                return b
        return self.config.buckets[-1]

    def _run_batch(self, batch):
        from ..observability import spans, trace

        t0 = time.perf_counter()
        n = len(batch)
        bucket = self._bucket_for(n)
        # queue wait ends the moment the batch forms: recorded per
        # request under ITS trace (the capture/activate handoff — this
        # runs on the scheduler thread, the context was captured at
        # ingest), so "where did this request's latency go" splits into
        # queue-wait vs dispatch from the trace alone
        for r in batch:
            spans.record(
                "serving.queue_wait", t0 - r.t_enqueue,
                category="serving", ctx=r.ctx,
                args={"endpoint": self.name, "batch_size": n},
            )
        try:
            feed = {}
            for name in self.runner.feed_names:
                rows = np.stack([r.feeds[name] for r in batch])
                if n < bucket:
                    pad = np.zeros(
                        (bucket - n,) + rows.shape[1:], rows.dtype
                    )
                    rows = np.concatenate([rows, pad], axis=0)
                feed[name] = rows
            with self._run_lock:
                # the live dispatch span (and everything the runner
                # records inside: executor.step, GPT prefill/decode)
                # files under the FIRST request's trace; the other
                # requests get their dispatch share recorded
                # retrospectively below, so every trace is complete
                with trace.activate(batch[0].ctx), \
                        self._obs.span("serving.batch", category="serving",
                                       endpoint=self.name, bucket=bucket,
                                       batch_size=n):
                    outs = [np.asarray(o) for o in self.runner.run(feed)]
        except Exception as exc:
            self._obs.add("serving.request_errors", n)
            for r in batch:
                r.future.set_exception(exc)
            return
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        for r in batch:
            spans.record(
                "serving.dispatch", now - t0, category="serving",
                ctx=r.ctx,
                args={"endpoint": self.name, "bucket": bucket,
                      "batch_size": n},
            )
        self._obs.add("serving.batches")
        self._obs.add(f"serving.batches.{self.name}")
        self._obs.add(f"serving.bucket_runs.{self.name}.{bucket}")
        self._obs.observe("serving.batch_latency", dt)
        self._obs.observe(
            "serving.batch_fill", n / bucket, buckets=_RATIO_BUCKETS
        )
        self._obs.observe(
            "serving.padding_waste", (bucket - n) / bucket,
            buckets=_RATIO_BUCKETS,
        )
        self._obs.add("serving.padded_rows", bucket - n)
        for i, r in enumerate(batch):
            r.future.set_result([o[i] for o in outs])
            lat = now - r.t_enqueue
            self._obs.observe("serving.request_latency", lat)
            self._obs.observe(f"serving.request_latency.{self.name}", lat)
        self._obs.add("serving.requests_served", n)

    # -- warmup ------------------------------------------------------------
    def warmup(self):
        """Compile the EXACT (bucket-shape, fetch-set) executables serving
        will dispatch: one zero-feed run per bucket through the same
        ``runner.run`` entry the scheduler uses. The executor's executable
        cache (and its flops/estimate digests) key on the fetch set, so a
        warmup with a different fetch list — or a different batch shape —
        would leave every real bucket cold and push the first compile into
        a user-visible request latency (the PR-6 bench warmup lesson)."""
        from ..core.dtypes import to_numpy_dtype

        for b in self.config.buckets:
            feed = {}
            for name in self.runner.feed_names:
                shape, dtype = self.runner.sample_spec(name)
                feed[name] = np.zeros((b,) + shape, to_numpy_dtype(dtype))
            with self._run_lock:
                self.runner.run(feed)
            self._obs.add("serving.warmup_runs")
        return len(self.config.buckets)

    # -- lifecycle ---------------------------------------------------------
    def pending(self):
        with self._cond:
            return len(self._queue)

    def drain(self, timeout=None):
        """Stop admitting, flush the queue through the scheduler, stop the
        thread. Returns True when everything in flight completed."""
        with self._cond:
            self._draining = True
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout)
        return not self._thread.is_alive() and not self._queue


class Server:
    """A set of endpoints behind one admission/drain lifecycle."""

    def __init__(self):
        self._endpoints = {}
        self._draining = False
        self._drained = threading.Event()
        self._lock = threading.Lock()

    def add_endpoint(self, name, runner, config=None, frozen=None,
                     executor=None, scope=None):
        """Register (and start) an endpoint. Pass a ``runner`` with the
        FrozenRunner interface, or ``frozen=`` to wrap a FrozenModel."""
        if frozen is not None:
            runner = FrozenRunner(frozen, executor=executor, scope=scope)
        if runner is None:
            raise InvalidArgumentError(
                "add_endpoint needs runner= or frozen="
            )
        with self._lock:
            if self._draining:
                raise ServerDrainingError("server is draining")
            if name in self._endpoints:
                raise InvalidArgumentError(
                    f"endpoint {name!r} already registered"
                )
            ep = Endpoint(name, runner, config)
            self._endpoints[name] = ep
        return ep

    def __getitem__(self, name):
        return self._endpoints[name]

    def endpoints(self):
        return dict(self._endpoints)

    def submit(self, endpoint, feeds):
        if self._draining:
            from .. import observability as _obs

            _obs.add("serving.rejected")
            raise ServerDrainingError("server is draining")
        return self._endpoints[endpoint].submit(feeds)

    def warmup(self):
        """Warm every endpoint's bucket executables; returns total runs."""
        return sum(ep.warmup() for ep in self._endpoints.values())

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=None):
        """Graceful shutdown: stop admission, complete every admitted
        request, stop scheduler threads, then bump ``serving.drained``.
        Idempotent; returns True when fully drained."""
        from .. import observability as _obs

        with self._lock:
            first = not self._draining
            self._draining = True
            eps = list(self._endpoints.values())
        ok = True
        for ep in eps:
            ok = ep.drain(timeout) and ok
        if first:
            _obs.add("serving.drained")
            _obs.set_gauge("serving.draining", 1.0)
        if ok:
            self._drained.set()
        return ok

    def wait_drained(self, timeout=None):
        return self._drained.wait(timeout)


def install_preemption_handler(server, exit_on_drain=True, timeout=None):
    """SIGTERM -> drain -> exit ``PREEMPTION_EXIT_CODE`` (75), riding the
    PR-3 preemption contract: the launcher treats 75 as a clean preempt
    (no restart-budget burn). The signal handler only spawns the drain
    thread (handlers must stay tiny); with ``exit_on_drain=False`` the
    caller observes ``server.wait_drained()`` instead — the in-process
    test shape."""
    import os
    import signal

    from ..resilience.health import PREEMPTION_EXIT_CODE

    def _drain_then_exit():
        server.drain(timeout)
        if exit_on_drain:
            # handlers/threads cannot sys.exit the main thread; preemption
            # wants no further cleanup anyway (checkpointless server)
            os._exit(PREEMPTION_EXIT_CODE)

    def _on_sigterm(signum, frame):
        threading.Thread(
            target=_drain_then_exit, daemon=True,
            name="serving-drain",
        ).start()

    old = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _on_sigterm)
    return old
