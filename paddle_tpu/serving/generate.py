"""KV-cache GPT generation: prefill + single-token decode programs.

``GPTGenerator`` owns the two programs ``models/gpt.py`` splits the
decoder into and the Scope their cache persistables share:

* prefill — embed the [B, S] context ONCE, fill every layer's
  ``gpt_l{i}_cache_{k,v}`` persistable rows 0..S-1, emit the last
  position's logits;
* decode — embed ONE token at a runtime position, append its K/V rows to
  the caches (in-place: the Executor donates mutated persistables, so the
  update is an HBM dynamic-update-slice), attend over the cache, emit
  next-token logits.

Generation is O(1) recompute per token instead of O(S): both programs
compile exactly once (shapes never change across steps), so a T-token
generation is 1 prefill dispatch + T-1 decode dispatches against warm
executables. ``generate_full_recompute`` keeps the naive re-run-the-
whole-context baseline alive for parity tests and the bench_serving
speedup measurement.
"""

from __future__ import annotations

import numpy as np

from ..errors import InvalidArgumentError


class GPTGenerator:
    """Checkpoint -> tokens through the KV-cache decode path.

    Shapes are fixed at construction (the serving bucket contract):
    `batch` concurrent sequences, `context_len` prompt tokens, caches
    sized `max_len`. ``generate`` emits up to
    ``max_len - context_len`` tokens.
    """

    def __init__(self, cfg, batch, context_len, max_len, scope=None,
                 executor=None):
        import paddle_tpu as fluid
        from ..framework.scope import Scope, scope_guard
        from ..models.gpt import gpt_decode_step, gpt_prefill

        if context_len >= max_len:
            raise InvalidArgumentError(
                f"context_len {context_len} must leave room to generate "
                f"(max_len {max_len})"
            )
        self.cfg = cfg
        self.batch = int(batch)
        self.context_len = int(context_len)
        self.max_len = int(max_len)
        self.scope = scope or Scope()
        self.executor = executor or fluid.Executor()

        self.prefill_prog = fluid.Program()
        self.startup_prog = fluid.Program()
        with fluid.program_guard(self.prefill_prog, self.startup_prog):
            ids = fluid.data("context_ids", [batch, context_len], "int64")
            logits = gpt_prefill(ids, cfg, max_len)
        self._prefill_fetch = [logits.name]

        self.decode_prog = fluid.Program()
        decode_startup = fluid.Program()  # same init ops; never run
        with fluid.program_guard(self.decode_prog, decode_startup):
            tok = fluid.data("token_ids", [batch, 1], "int64")
            pos = fluid.data("pos_ids", [1, 1], "int64")
            dlogits = gpt_decode_step(tok, pos, cfg, max_len)
        self._decode_fetch = [dlogits.name]

        # both are pure inference graphs: mark them so the Executor traces
        # in test mode and the verifier holds the inference contract
        self.prefill_prog._is_inference = True
        self.decode_prog._is_inference = True
        self._scope_guard = scope_guard

    def _param_vars(self):
        from ..models.gpt import gpt_cache_names

        caches = set(gpt_cache_names(self.cfg))
        return [
            v for v in self.prefill_prog.list_vars()
            if v.persistable and v.name not in caches
        ]

    # -- parameters --------------------------------------------------------
    def init_params(self, seed=0):
        """Random-init parameters (bench/test path; production loads a
        checkpoint). Runs the prefill startup program once."""
        self.startup_prog.random_seed = seed
        self.prefill_prog.random_seed = seed
        self.decode_prog.random_seed = seed
        with self._scope_guard(self.scope):
            self.executor.run(self.startup_prog, scope=self.scope)
        self.reset()

    def load_params(self, path):
        """Load trained GPT parameters (``io.save`` format) into the
        shared scope — cache vars excluded (they are runtime state, not
        checkpoint content)."""
        from .. import io as _io

        with self._scope_guard(self.scope):
            _io.load(self.prefill_prog, path, var_list=self._param_vars())
        self.reset()

    def save_params(self, path):
        from .. import io as _io

        with self._scope_guard(self.scope):
            return _io.save(self.prefill_prog, path)

    def reset(self):
        """Zero the KV caches (fresh generation state)."""
        import jax.numpy as jnp

        from ..models.gpt import gpt_cache_names

        shape = (self.batch, self.max_len, self.cfg.hidden_size)
        for name in gpt_cache_names(self.cfg):
            self.scope.set_var(name, jnp.zeros(shape, jnp.float32))

    # -- generation --------------------------------------------------------
    def generate(self, context_ids, max_new_tokens, greedy=True):
        """Generate `max_new_tokens` per sequence; returns [B, T] int64.

        Greedy decoding (argmax) — the deterministic contract the parity
        tests rely on; sampling policies plug in at the caller by reading
        logits instead."""
        from .. import observability as _obs

        if not greedy:
            raise InvalidArgumentError(
                "only greedy decoding is implemented; sample from the "
                "logits fetch at the caller for other policies"
            )
        if int(max_new_tokens) < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        ids = np.asarray(context_ids)
        if ids.shape != (self.batch, self.context_len):
            raise InvalidArgumentError(
                f"context_ids must be [{self.batch}, {self.context_len}], "
                f"got {ids.shape}"
            )
        t_total = self.context_len + int(max_new_tokens)
        if t_total > self.max_len:
            raise InvalidArgumentError(
                f"context {self.context_len} + {max_new_tokens} new tokens "
                f"exceeds max_len {self.max_len}"
            )
        self.reset()
        # child spans under the caller's trace (the serving router
        # activates the request's context around runner.run): the
        # prefill/decode split of a generate request's latency — each
        # executor.step inside nests one level further
        with self._scope_guard(self.scope):
            with _obs.span("serving.prefill", category="serving",
                           context_len=self.context_len):
                (logits,) = self.executor.run(
                    self.prefill_prog, feed={"context_ids": ids},
                    fetch_list=self._prefill_fetch, scope=self.scope,
                )
            _obs.add("serving.prefill_steps")
            out = np.zeros((self.batch, max_new_tokens), np.int64)
            nxt = np.argmax(np.asarray(logits)[:, -1, :], axis=-1)
            out[:, 0] = nxt
            with _obs.span("serving.decode_loop", category="serving",
                           tokens=int(max_new_tokens)):
                for t in range(1, max_new_tokens):
                    # position of the fed token
                    pos = self.context_len + t - 1
                    (logits,) = self.executor.run(
                        self.decode_prog,
                        feed={
                            "token_ids": nxt[:, None].astype(np.int64),
                            "pos_ids": np.array([[pos]], np.int64),
                        },
                        fetch_list=self._decode_fetch, scope=self.scope,
                    )
                    nxt = np.argmax(np.asarray(logits)[:, -1, :], axis=-1)
                    out[:, t] = nxt
            _obs.add("serving.decode_steps", max(0, max_new_tokens - 1))
        return out

    def generate_full_recompute(self, context_ids, max_new_tokens):
        """The naive baseline: re-run the FULL context through a plain
        ``gpt_logits`` graph for every emitted token (one fixed padded
        shape, so it too compiles once — the comparison isolates
        recompute cost, not compile count)."""
        import paddle_tpu as fluid
        from ..models.gpt import gpt_logits

        ids = np.asarray(context_ids)
        if int(max_new_tokens) < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        t_total = self.context_len + int(max_new_tokens)
        if t_total > self.max_len:
            raise InvalidArgumentError(
                f"context {self.context_len} + {max_new_tokens} new tokens "
                f"exceeds max_len {self.max_len}"
            )
        prog = getattr(self, "_recompute_prog", None)
        if prog is None or self._recompute_len != t_total:
            cfg = self.cfg
            prog = fluid.Program()
            startup = fluid.Program()  # params come from the shared scope
            with fluid.program_guard(prog, startup):
                full = fluid.data("full_ids", [self.batch, t_total],
                                  "int64")
                logits = gpt_logits(full, cfg, is_test=True)
            prog._is_inference = True
            self._recompute_prog = prog
            self._recompute_len = t_total
            self._recompute_fetch = [logits.name]
        prog = self._recompute_prog
        padded = np.zeros((self.batch, t_total), np.int64)
        padded[:, : self.context_len] = ids
        out = np.zeros((self.batch, max_new_tokens), np.int64)
        cur = self.context_len
        with self._scope_guard(self.scope):
            for t in range(max_new_tokens):
                (logits,) = self.executor.run(
                    prog, feed={"full_ids": padded},
                    fetch_list=self._recompute_fetch, scope=self.scope,
                )
                nxt = np.argmax(np.asarray(logits)[:, cur - 1, :], axis=-1)
                out[:, t] = nxt
                if cur < t_total:
                    padded[:, cur] = nxt
                cur += 1
        return out


class GPTGenerateRunner:
    """Router runner wrapping a GPTGenerator: a "generate" endpoint whose
    batched dispatch is one prefill + T decode steps. The endpoint bucket
    must equal the generator's batch (cache shapes are static)."""

    def __init__(self, generator, max_new_tokens):
        self.generator = generator
        self.max_new_tokens = int(max_new_tokens)
        self.feed_names = ("context_ids",)

    def validate_config(self, config):
        """Endpoint hook: cache shapes are static, so every configured
        bucket must equal the generator's batch exactly."""
        bad = [b for b in config.buckets if b != self.generator.batch]
        if bad:
            raise InvalidArgumentError(
                f"GPT generate endpoint buckets {config.buckets} must all "
                f"equal the generator batch {self.generator.batch} (cache "
                "shapes are compiled static)"
            )

    def sample_spec(self, name):
        return (self.generator.context_len,), "int64"

    def run(self, feed):
        return [
            self.generator.generate(
                feed["context_ids"], self.max_new_tokens
            )
        ]
