// Native data-feed hot path (reference C++: framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance ~:632, operators/reader/
// buffered_reader.cc): GIL-free parsing of MultiSlot text records and
// ragged->padded packing (the LoD -> static-shape edge operation of the
// TPU design, SURVEY §7 hard-part #1).
//
// Record format (the reference's MultiSlot schema): one instance per line,
// per slot "<n> v1 v2 ... vn" fields separated by spaces; slots
// concatenated left to right. Values parse as DOUBLE so integer id slots
// round-trip exactly below 2^53 (CTR id spaces fit comfortably); the
// padded packers then emit float32 or exact int64.
//
// Built by paddle_tpu/native/__init__.py with g++ -O3 -shared -fPIC and
// loaded via ctypes (no pybind11 in this image); a numpy fallback keeps
// the package importable without a toolchain.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse newline-separated MultiSlot records.
//   buf/len:        input text
//   num_slots:      slots per instance
//   out_vals:       flat value output, capacity max_vals
//   out_offsets:    CSR offsets per (record, slot):
//                   size max_records*num_slots+1; out_offsets[0] = 0
//   returns number of complete records parsed, or -1 on malformed input,
//   -2 on capacity overflow.
long ps_parse_multislot(const char* buf, long len, int num_slots,
                        double* out_vals, long max_vals,
                        long* out_offsets, long max_records) {
  long n_vals = 0;
  long n_records = 0;
  long cell = 0;
  out_offsets[0] = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    // skip blank lines
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    if (n_records >= max_records) return -2;
    // a record must be complete within ITS line: strtol/strtod would skip
    // '\n' as whitespace and silently pull tokens from the next record, so
    // skip field separators manually and treat newline as a hard stop
    bool bad = false;
    for (int s = 0; s < num_slots && !bad; ++s) {
      char* next = nullptr;
      while (p < end && (*p == ' ' || *p == '\t')) ++p;
      if (p >= end || *p == '\n' || *p == '\r') { bad = true; break; }
      long n = strtol(p, &next, 10);
      if (next == p || n < 0) { bad = true; break; }
      p = next;
      for (long i = 0; i < n; ++i) {
        if (n_vals >= max_vals) return -2;
        while (p < end && (*p == ' ' || *p == '\t')) ++p;
        if (p >= end || *p == '\n' || *p == '\r') { bad = true; break; }
        double v = strtod(p, &next);
        if (next == p) { bad = true; break; }
        out_vals[n_vals++] = v;
        p = next;
      }
      if (!bad) out_offsets[++cell] = n_vals;
    }
    if (bad) return -1;
    // consume to end of line
    while (p < end && *p != '\n') ++p;
    ++n_records;
  }
  return n_records;
}

// Ragged -> padded: pack CSR (vals, offsets) rows into [n_rows, max_len]
// with pad_value, writing per-row lengths. float32 variant.
void ps_pack_padded_f32(const float* vals, const long* offsets, long n_rows,
                        long max_len, float pad_value, float* out,
                        int32_t* lengths) {
  for (long r = 0; r < n_rows; ++r) {
    long lo = offsets[r], hi = offsets[r + 1];
    long n = hi - lo;
    if (n > max_len) n = max_len;
    lengths[r] = (int32_t)n;
    float* row = out + r * max_len;
    for (long i = 0; i < n; ++i) row[i] = vals[lo + i];
    for (long i = n; i < max_len; ++i) row[i] = pad_value;
  }
}

// int64 variant (exact ids for embedding lookups).
void ps_pack_padded_i64(const int64_t* vals, const long* offsets,
                        long n_rows, long max_len, int64_t pad_value,
                        int64_t* out, int32_t* lengths) {
  for (long r = 0; r < n_rows; ++r) {
    long lo = offsets[r], hi = offsets[r + 1];
    long n = hi - lo;
    if (n > max_len) n = max_len;
    lengths[r] = (int32_t)n;
    int64_t* row = out + r * max_len;
    for (long i = 0; i < n; ++i) row[i] = vals[lo + i];
    for (long i = n; i < max_len; ++i) row[i] = pad_value;
  }
}

}  // extern "C"
