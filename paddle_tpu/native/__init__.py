"""Native (C++) data-feed components, loaded via ctypes.

Reference parity: the C++ Dataset/DataFeed pipeline (framework/data_feed.cc
MultiSlot parsing, data_set.cc loading threads) and buffered_reader.cc. The
compute path is XLA; this is the host-side runtime piece the reference also
kept native because Python-level parsing is the bottleneck of CTR-style
training.

Build: compiled on first use with g++ (-O3 -shared -fPIC) into
_libpaddle_native.so beside the source, cached by source mtime. pybind11 is
not in this image, so the ABI is plain C + ctypes. Without a toolchain the
numpy fallback keeps everything working (slower, same semantics) —
`native_available()` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "multislot.cpp"),
    os.path.join(_HERE, "crypto.cpp"),
]
_LIB = os.path.join(_HERE, "_libpaddle_native.so")

_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *_SRCS, "-o", _LIB]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < max(
            os.path.getmtime(s) for s in _SRCS
        ):
            _build()
        _lib = _bind(ctypes.CDLL(_LIB))
    except AttributeError:
        # stale prebuilt .so missing newly added symbols (mtime races on
        # rsync'd checkouts): force one rebuild, else fall back to Python
        try:
            _build()
            _lib = _bind(ctypes.CDLL(_LIB))
        except (OSError, subprocess.CalledProcessError, AttributeError):
            _lib = None
    except (OSError, subprocess.CalledProcessError):
        _lib = None
    return _lib


def _bind(lib):
    """Declare ctypes signatures; AttributeError here means a stale .so."""
    lib.ps_parse_multislot.restype = ctypes.c_long
    lib.ps_parse_multislot.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.c_long,
        ctypes.POINTER(ctypes.c_long), ctypes.c_long,
    ]
    lib.ps_pack_padded_f32.restype = None
    lib.ps_pack_padded_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long),
        ctypes.c_long, ctypes.c_long, ctypes.c_float,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.ps_pack_padded_i64.restype = None
    lib.ps_pack_padded_i64.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_long),
        ctypes.c_long, ctypes.c_long, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pd_aes_block_encrypt.restype = ctypes.c_int
    lib.pd_aes_block_encrypt.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.pd_aes_ctr_crypt.restype = ctypes.c_int
    lib.pd_aes_ctr_crypt.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
    ]
    return lib


def native_available():
    return _load() is not None


def aes_block_encrypt(key: bytes, block: bytes):
    """One AES block through the native core; None if native is absent."""
    lib = _load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 16)()
    rc = lib.pd_aes_block_encrypt(key, len(key), bytes(block), out)
    if rc != 0:
        raise ValueError(f"bad AES key length {len(key)}")
    return bytes(out)


def aes_ctr_crypt(key: bytes, iv: bytes, data: bytes):
    """AES-CTR over data (encrypt == decrypt); None if native is absent."""
    lib = _load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    rc = lib.pd_aes_ctr_crypt(key, len(key), bytes(iv), buf, len(data))
    if rc != 0:
        raise ValueError(f"bad AES key length {len(key)}")
    return bytes(buf)


def parse_multislot(text, num_slots):
    """Parse MultiSlot records -> (flat float64 values — exact for int ids
    below 2**53 — and CSR offsets [n_records*num_slots+1])."""
    if isinstance(text, str):
        text = text.encode()
    lib = _load()
    if lib is None:
        return _parse_multislot_py(text, num_slots)
    max_vals = max(len(text), 16)  # a value needs >=2 bytes of text
    max_records = max(text.count(b"\n") + 1, 1)
    vals = np.empty(max_vals, np.float64)
    offs = np.empty(max_records * num_slots + 1, np.int64)
    n = lib.ps_parse_multislot(
        text, len(text), num_slots,
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_vals,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), max_records,
    )
    if n == -1:
        raise ValueError("malformed MultiSlot record")
    if n == -2:
        raise ValueError("MultiSlot capacity overflow")
    n_cells = n * num_slots
    return vals[: offs[n_cells]].copy(), offs[: n_cells + 1].copy()


def _parse_multislot_py(text, num_slots):
    """Numpy fallback with identical semantics."""
    vals, offs = [], [0]
    for line in text.splitlines():
        tok = line.split()
        if not tok:
            continue
        i = 0
        for _ in range(num_slots):
            if i >= len(tok):
                raise ValueError("malformed MultiSlot record")
            n = int(tok[i])
            i += 1
            if n < 0 or i + n > len(tok):
                raise ValueError("malformed MultiSlot record")
            vals.extend(float(t) for t in tok[i:i + n])
            i += n
            offs.append(len(vals))
    return np.asarray(vals, np.float64), np.asarray(offs, np.int64)


def pack_padded(vals, offsets, max_len, pad_value=0, dtype=np.float32):
    """CSR ragged rows -> ([n_rows, max_len] padded, [n_rows] lengths)."""
    offsets = np.ascontiguousarray(offsets, np.int64)
    n_rows = len(offsets) - 1
    lengths = np.empty(n_rows, np.int32)
    lib = _load()
    dtype = np.dtype(dtype)
    if lib is not None and dtype in (np.dtype(np.float32), np.dtype(np.int64)):
        if dtype == np.float32:
            vals = np.ascontiguousarray(vals, np.float32)
            out = np.empty((n_rows, max_len), np.float32)
            lib.ps_pack_padded_f32(
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
                n_rows, max_len, ctypes.c_float(float(pad_value)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        else:
            vals = np.ascontiguousarray(vals, np.int64)
            out = np.empty((n_rows, max_len), np.int64)
            lib.ps_pack_padded_i64(
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
                n_rows, max_len, ctypes.c_int64(int(pad_value)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        return out, lengths
    # fallback
    out = np.full((n_rows, max_len), pad_value, dtype)
    for r in range(n_rows):
        row = np.asarray(vals[offsets[r]:offsets[r + 1]])[:max_len]
        out[r, : len(row)] = row.astype(dtype)
        lengths[r] = len(row)
    return out, lengths
