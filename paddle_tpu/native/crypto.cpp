// AES-128/192/256 block cipher + CTR mode, C ABI for ctypes.
//
// Reference parity: paddle/fluid/framework/io/crypto/aes_cipher.cc — the
// reference links cryptopp for AES-GCM model-file encryption; this image
// vendors no crypto library, so the primitive is implemented here (FIPS-197
// key expansion + rounds, validated against the FIPS/NIST known-answer
// vectors in tests/test_crypto.py). Authentication is done Python-side with
// HMAC-SHA256 (encrypt-then-MAC), see paddle_tpu/crypto.py.

#include <cstdint>
#include <cstring>

namespace {

const uint8_t SBOX[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67, 0x2b,
    0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59, 0x47, 0xf0,
    0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7, 0xfd, 0x93, 0x26,
    0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1, 0x71, 0xd8, 0x31, 0x15,
    0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05, 0x9a, 0x07, 0x12, 0x80, 0xe2,
    0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83, 0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0,
    0x52, 0x3b, 0xd6, 0xb3, 0x29, 0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed,
    0x20, 0xfc, 0xb1, 0x5b, 0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf,
    0xd0, 0xef, 0xaa, 0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f,
    0x50, 0x3c, 0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5,
    0xbc, 0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19, 0x73,
    0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee, 0xb8, 0x14,
    0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49, 0x06, 0x24, 0x5c,
    0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79, 0xe7, 0xc8, 0x37, 0x6d,
    0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4, 0xea, 0x65, 0x7a, 0xae, 0x08,
    0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6, 0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f,
    0x4b, 0xbd, 0x8b, 0x8a, 0x70, 0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e,
    0x61, 0x35, 0x57, 0xb9, 0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11,
    0x69, 0xd9, 0x8e, 0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf,
    0x8c, 0xa1, 0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f,
    0xb0, 0x54, 0xbb, 0x16};

inline uint8_t xtime(uint8_t x) {
  return static_cast<uint8_t>((x << 1) ^ ((x >> 7) * 0x1b));
}

struct AesKey {
  uint8_t rk[15][16];  // round keys
  int rounds;
};

// FIPS-197 key expansion (Nk words in, 4*(rounds+1) words out)
int expand_key(const uint8_t* key, int key_len, AesKey* out) {
  int nk = key_len / 4;
  if (key_len != 16 && key_len != 24 && key_len != 32) return -1;
  out->rounds = nk + 6;
  int total_words = 4 * (out->rounds + 1);
  uint8_t w[60][4];
  std::memcpy(w, key, key_len);
  uint8_t rcon = 1;
  for (int i = nk; i < total_words; ++i) {
    uint8_t t[4];
    std::memcpy(t, w[i - 1], 4);
    if (i % nk == 0) {
      uint8_t tmp = t[0];  // RotWord
      t[0] = SBOX[t[1]] ^ rcon;
      t[1] = SBOX[t[2]];
      t[2] = SBOX[t[3]];
      t[3] = SBOX[tmp];
      rcon = xtime(rcon);
    } else if (nk > 6 && i % nk == 4) {
      for (int j = 0; j < 4; ++j) t[j] = SBOX[t[j]];
    }
    for (int j = 0; j < 4; ++j) w[i][j] = w[i - nk][j] ^ t[j];
  }
  std::memcpy(out->rk, w, total_words * 4);
  return 0;
}

void encrypt_block(const AesKey& k, const uint8_t in[16], uint8_t out[16]) {
  uint8_t s[16];
  for (int i = 0; i < 16; ++i) s[i] = in[i] ^ k.rk[0][i];
  for (int round = 1; round <= k.rounds; ++round) {
    // SubBytes + ShiftRows (column-major state: s[4*col + row])
    uint8_t t[16];
    for (int c = 0; c < 4; ++c)
      for (int r = 0; r < 4; ++r)
        t[4 * c + r] = SBOX[s[4 * ((c + r) & 3) + r]];
    if (round < k.rounds) {
      for (int c = 0; c < 4; ++c) {  // MixColumns
        uint8_t a0 = t[4 * c], a1 = t[4 * c + 1], a2 = t[4 * c + 2],
                a3 = t[4 * c + 3];
        uint8_t x = a0 ^ a1 ^ a2 ^ a3;
        s[4 * c] = a0 ^ x ^ xtime(static_cast<uint8_t>(a0 ^ a1));
        s[4 * c + 1] = a1 ^ x ^ xtime(static_cast<uint8_t>(a1 ^ a2));
        s[4 * c + 2] = a2 ^ x ^ xtime(static_cast<uint8_t>(a2 ^ a3));
        s[4 * c + 3] = a3 ^ x ^ xtime(static_cast<uint8_t>(a3 ^ a0));
      }
    } else {
      std::memcpy(s, t, 16);
    }
    for (int i = 0; i < 16; ++i) s[i] ^= k.rk[round][i];
  }
  std::memcpy(out, s, 16);
}

}  // namespace

extern "C" {

int pd_aes_block_encrypt(const uint8_t* key, int key_len,
                         const uint8_t in[16], uint8_t out[16]) {
  AesKey k;
  if (expand_key(key, key_len, &k) != 0) return -1;
  encrypt_block(k, in, out);
  return 0;
}

// CTR mode, in place (encrypt == decrypt): keystream = AES(counter),
// counter = iv treated as a 128-bit big-endian integer, incremented per
// block (NIST SP 800-38A).
int pd_aes_ctr_crypt(const uint8_t* key, int key_len, const uint8_t iv[16],
                     uint8_t* buf, long n) {
  AesKey k;
  if (expand_key(key, key_len, &k) != 0) return -1;
  uint8_t ctr[16], ks[16];
  std::memcpy(ctr, iv, 16);
  for (long off = 0; off < n; off += 16) {
    encrypt_block(k, ctr, ks);
    long m = (n - off < 16) ? n - off : 16;
    for (long i = 0; i < m; ++i) buf[off + i] ^= ks[i];
    for (int i = 15; i >= 0; --i)
      if (++ctr[i] != 0) break;
  }
  return 0;
}

}  // extern "C"
