"""DataFeeder (reference python/paddle/fluid/data_feeder.py): converts
row-oriented python samples into the column-oriented feed dict Executor.run
expects, casting to each feed Variable's declared dtype/shape."""

from __future__ import annotations

import numpy as np

from .core.dtypes import to_numpy_dtype
from .framework.program import Variable, default_main_program


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.program = program or default_main_program()
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                v = self.program.global_block.var(v)
            if not isinstance(v, Variable):
                raise TypeError("feed_list entries must be Variables/names")
            self.feed_vars.append(v)

    def feed(self, iterable):
        """iterable of rows, each row = one value per feed var (tuple/list),
        -> {name: batched ndarray} (reference DataFeeder.feed)."""
        columns = [[] for _ in self.feed_vars]
        for row in iterable:
            if not isinstance(row, (list, tuple)):
                row = (row,)
            if len(row) != len(self.feed_vars):
                raise ValueError(
                    f"sample has {len(row)} fields, feed_list expects "
                    f"{len(self.feed_vars)}"
                )
            for c, v in zip(columns, row):
                c.append(np.asarray(v))
        out = {}
        for var, col in zip(self.feed_vars, columns):
            arr = np.stack(col).astype(to_numpy_dtype(var.dtype))
            want = var.shape or ()

            def ok(shape):
                return len(want) == len(shape) and all(
                    w in (-1, None) or w == a for w, a in zip(want, shape)
                )

            if ok(arr.shape):
                pass
            elif len(want) == arr.ndim + 1 and want[-1] in (1, -1):
                arr = arr.reshape(arr.shape + (1,))
                if not ok(arr.shape):
                    raise ValueError(
                        f"feed {var.name!r}: samples batch to "
                        f"{arr.shape}, variable declares {tuple(want)}"
                    )
            else:
                raise ValueError(
                    f"feed {var.name!r}: samples batch to {arr.shape}, "
                    f"variable declares {tuple(want)}"
                )
            out[var.name] = arr
        return out
