"""Chrome-trace export of a profiler capture (reference tools/timeline.py:
_ChromeTraceFormatter :36 / Timeline :131 converted the profiler proto to
chrome://tracing JSON).

Here the input is a jax.profiler xplane directory (paddle_tpu.profiler
start/stop); every device/host event becomes a complete ("X") trace event
with plane->pid, line->tid mapping — load the output in chrome://tracing
or Perfetto."""

from __future__ import annotations

import glob
import json
import os


class _ChromeTraceFormatter:
    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append(
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": name}}
        )

    def emit_tid(self, name, pid, tid):
        self._metadata.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": name}}
        )

    def emit_region(self, ts_us, dur_us, pid, tid, category, name, args=None):
        self._events.append(
            {"ph": "X", "cat": category, "name": name, "pid": pid,
             "tid": tid, "ts": ts_us, "dur": dur_us, "args": args or {}}
        )

    def format_to_string(self, pretty=False):
        return json.dumps(
            {"traceEvents": self._metadata + self._events},
            indent=4 if pretty else None,
        )


class Timeline:
    def __init__(self, trace_dir, include_host_spans=False):
        self.trace_dir = trace_dir
        # merge the observability span ring buffer as an extra process, so
        # app-level spans (executor.step, fleet.minimize, user span()s) land
        # in ONE Perfetto-loadable JSON next to the device trace
        self.include_host_spans = include_host_spans

    def generate_chrome_trace(self):
        from jax.profiler import ProfileData

        files = sorted(
            glob.glob(
                os.path.join(self.trace_dir, "**", "*.xplane.pb"),
                recursive=True,
            )
        )
        if not files:
            raise FileNotFoundError(
                f"no xplane capture under {self.trace_dir}"
            )
        with open(files[-1], "rb") as f:
            pd = ProfileData.from_serialized_xspace(f.read())
        fmt = _ChromeTraceFormatter()
        n_planes = 0
        for pid, plane in enumerate(pd.planes):
            n_planes = pid + 1
            fmt.emit_pid(plane.name, pid)
            for tid, line in enumerate(plane.lines):
                fmt.emit_tid(line.name, pid, tid)
                for ev in line.events:
                    fmt.emit_region(
                        ev.start_ns / 1e3,
                        ev.duration_ns / 1e3,
                        pid,
                        tid,
                        "op",
                        ev.name[:120],
                    )
        if self.include_host_spans:
            from ..observability import spans as _spans

            _spans.emit_into(fmt, pid=n_planes)
        return fmt.format_to_string()

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.generate_chrome_trace())
        return path
