"""Reusable child-process supervision core (the launcher loop, extracted).

PR 2/3 grew a battle-tested supervision discipline inside
``distributed/launch.py``: spawn children, poll them on a scan loop,
declare a child HUNG when its heartbeat goes stale (SIGTERM, escalate to
SIGKILL after a grace period, never block the scan), and route deaths
through bounded full-jitter exponential restart backoff — each dead child
gets its own independent deadline so same-tick deaths neither share a
restart slot nor respawn in lockstep. The serving process fleet
(``serving/fleet.py``) needs exactly the same discipline for its worker
processes, so the loop now lives here as :class:`Supervisor` and both the
elastic launcher and the fleet consume it.

The class is policy-free where the two consumers differ:

* ``spawn(key, attempt)`` builds (or rebuilds) the child — the launcher
  passes its trainer spawner, the fleet its worker spawner;
* ``clean_exit(rc, hung)`` classifies a return code — the launcher
  treats ``PREEMPTION_EXIT_CODE`` (75) as clean only when the launcher
  did not itself kill the child as hung;
* ``restartable(key, rc, hung)`` gates the restart path BEFORE the
  budget — the launcher returns False for rank 0 (it owns the JAX
  coordination service; its death already doomed every peer) and for
  non-``--elastic`` pods.

:meth:`poll` is one scan tick: it never sleeps and returns the tick's
structured events (``hung`` / ``exit_clean`` / ``restart_scheduled`` /
``respawned`` / ``fatal``) so the caller owns logging, counters, and
abort decisions. Child bookkeeping rides the same ``_paddle_*`` Popen
attributes the launcher always used (``_paddle_spawned`` anchors
heartbeat staleness for children that die before their first beat,
``_paddle_hung`` taints the exit classification, ``_paddle_log`` is the
append-on-restart log handle), so fake-process tests drive the loop
unchanged.
"""

from __future__ import annotations

import signal
import subprocess
import time

__all__ = ["Supervisor", "kill_hung", "terminate_children"]

# child states (internal; exposed via Supervisor.state for introspection)
RUNNING = "running"
PENDING = "pending"  # dead, restart scheduled, waiting out its backoff
DONE = "done"        # exited clean; supervision over for this key
FAILED = "failed"    # not restartable / budget exhausted; left dead


def kill_hung(proc, grace=5.0):
    """SIGTERM a hung child, escalating to SIGKILL after `grace` without
    blocking the supervision scan (a rank stuck in a native collective
    routinely ignores SIGTERM forever). Call once per scan tick while the
    child stays both alive and stale."""
    if getattr(proc, "_paddle_kill_at", None) is None:
        proc._paddle_hung = True
        proc._paddle_kill_at = time.monotonic() + grace
        proc.send_signal(signal.SIGTERM)
    elif time.monotonic() >= proc._paddle_kill_at:
        proc.kill()


def terminate_children(procs, grace=10.0):
    """SIGTERM everyone, reap with a deadline, escalate to SIGKILL — a
    child blocked in a native collective often defers SIGTERM forever and
    would otherwise be orphaned holding its port. Closes the per-child
    ``_paddle_log`` handles."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    for p in procs:
        out = getattr(p, "_paddle_log", None)
        if out is not None:
            out.close()


class _Child:
    __slots__ = ("key", "proc", "state", "restarts", "deadline")

    def __init__(self, key, proc):
        self.key = key
        self.proc = proc
        self.state = RUNNING
        self.restarts = 0
        self.deadline = 0.0  # respawn-at (monotonic) while PENDING


class Supervisor:
    """Supervise a set of child processes with the launcher's discipline.

    ``spawn(key, attempt)`` must return a Popen-like object; attempt 0 is
    the first spawn, attempt N the Nth restart. ``staleness(proc,
    now_wall)`` (with ``stale_after > 0``) enables the hung-child
    watchdog: when it reports more seconds than ``stale_after``, the
    child is SIGTERM→SIGKILLed and its death routed through the restart
    path like any crash.
    """

    def __init__(self, spawn, *, max_restarts=3, backoff_base=0.5,
                 backoff_cap=10.0, staleness=None, stale_after=0.0,
                 clean_exit=None, restartable=None, kill_grace=5.0,
                 rng=None, clock=time.monotonic, wall=time.time):
        self._spawn = spawn
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._staleness = staleness
        self.stale_after = float(stale_after or 0.0)
        self._clean_exit = clean_exit or (lambda rc, hung: rc == 0)
        self._restartable = restartable or (lambda key, rc, hung: True)
        self.kill_grace = float(kill_grace)
        self._rng = rng
        self._clock = clock
        self._wall = wall
        self._children = {}  # key -> _Child, insertion-ordered

    # -- membership --------------------------------------------------------
    def add(self, key, attempt=0):
        """Spawn a new child under supervision; returns its proc."""
        proc = self._spawn(key, attempt)
        if getattr(proc, "_paddle_spawned", None) is None:
            proc._paddle_spawned = self._wall()
        self._children[key] = _Child(key, proc)
        return proc

    def adopt(self, key, proc):
        """Supervise an already-running child (the launcher's shape: it
        spawns the pod first, then hands the procs over)."""
        if getattr(proc, "_paddle_spawned", None) is None:
            proc._paddle_spawned = self._wall()
        self._children[key] = _Child(key, proc)

    def forget(self, key):
        """Stop supervising `key` (a deliberate scale-in: the caller owns
        the shutdown; no restart, no events). Returns its proc or None."""
        child = self._children.pop(key, None)
        return child.proc if child else None

    def proc(self, key):
        return self._children[key].proc

    def keys(self):
        return list(self._children)

    def restarts(self, key):
        return self._children[key].restarts

    def state(self, key):
        return self._children[key].state

    def some_active(self):
        """True while any child is running or awaiting a backoff respawn
        (the caller's loop-termination test)."""
        return any(
            c.state in (RUNNING, PENDING) for c in self._children.values()
        )

    def live_procs(self):
        return [
            c.proc for c in self._children.values() if c.state == RUNNING
        ]

    # -- the scan ----------------------------------------------------------
    def poll(self):
        """One supervision tick over every child, in insertion order.
        Never sleeps. Returns the tick's events, each a dict with at
        least ``kind`` / ``key`` / ``proc``:

        * ``hung`` — first detection of a stale heartbeat (the kill is
          already underway; emitted once per hang);
        * ``exit_clean`` — terminal; ``rc``;
        * ``restart_scheduled`` — death routed to backoff; ``rc``,
          ``hung``, ``attempt`` (1-based), ``delay``;
        * ``respawned`` — a scheduled restart's deadline arrived and the
          child was respawned; ``attempt``, ``proc`` is the NEW proc;
        * ``fatal`` — terminal: not restartable or budget exhausted;
          ``rc``, ``hung``, ``restarts``. The child is left dead; the
          caller decides whether that aborts the whole set.
        """
        from .retry import backoff_delay

        events = []
        now = self._clock()
        watch = self.stale_after > 0 and self._staleness is not None
        now_wall = self._wall() if watch else 0.0
        for child in list(self._children.values()):
            if child.state in (DONE, FAILED):
                continue
            proc = child.proc
            if child.state == PENDING:
                if now >= child.deadline:
                    log = getattr(proc, "_paddle_log", None)
                    if log is not None:
                        log.close()
                    child.proc = self._spawn(child.key, child.restarts)
                    if getattr(child.proc, "_paddle_spawned", None) is None:
                        child.proc._paddle_spawned = self._wall()
                    child.state = RUNNING
                    events.append({
                        "kind": "respawned", "key": child.key,
                        "proc": child.proc, "attempt": child.restarts,
                    })
                continue
            rc = proc.poll()
            if rc is None:
                if watch and self._staleness(proc, now_wall) \
                        > self.stale_after:
                    if getattr(proc, "_paddle_kill_at", None) is None:
                        events.append({
                            "kind": "hung", "key": child.key, "proc": proc,
                            "stale_after": self.stale_after,
                        })
                    kill_hung(proc, self.kill_grace)
                continue
            hung = getattr(proc, "_paddle_hung", False)
            if self._clean_exit(rc, hung):
                child.state = DONE
                events.append({
                    "kind": "exit_clean", "key": child.key, "proc": proc,
                    "rc": rc,
                })
                continue
            n = child.restarts
            if (not self._restartable(child.key, rc, hung)
                    or n >= self.max_restarts):
                child.state = FAILED
                events.append({
                    "kind": "fatal", "key": child.key, "proc": proc,
                    "rc": rc, "hung": hung, "restarts": n,
                })
                continue
            child.restarts = n + 1
            delay = backoff_delay(
                n + 1, self.backoff_base, self.backoff_cap, rng=self._rng
            )
            child.state = PENDING
            child.deadline = now + delay
            events.append({
                "kind": "restart_scheduled", "key": child.key, "proc": proc,
                "rc": rc, "hung": hung, "attempt": n + 1, "delay": delay,
            })
        return events

    # -- teardown ----------------------------------------------------------
    def terminate(self, grace=10.0):
        """Terminate every child (running or pending): SIGTERM → reap
        with a deadline → SIGKILL, close log handles, cancel pending
        restarts. Safe to call twice."""
        procs = [c.proc for c in self._children.values()]
        for c in self._children.values():
            if c.state in (RUNNING, PENDING):
                c.state = DONE
        terminate_children(procs, grace=grace)
