"""TrainGuard: the step-loop health guard (detect → skip → rollback → drain).

PR 2 made storage and workers crash-safe; this closes the loop for the
three ways the *training loop itself* dies on long TPU jobs:

* **numeric blow-up** — every step the guard checks the fetched values
  (loss, grad norms, whatever the user fetches) with ONE fused on-device
  ``jnp.isfinite`` reduction. That is the cheap always-on path; the
  per-op ``FLAGS_check_nan_inf`` executor mode stays available for
  debugging *which* op went bad. A bad step is **skipped**: the guard
  restores the pre-step parameter/optimizer state it snapshotted (device
  copies of the program's persistables; jax arrays are immutable so this
  is one device-to-device copy per step), bumps ``resilience.bad_steps``,
  and feeds the AMP dynamic-loss-scale automaton
  (``OptimizerWithMixedPrecision.note_step``) so fp16 users get scale
  decay for free. After `max_bad_steps` CONSECUTIVE bad steps — the same
  state keeps reproducing the NaN, so skipping cannot help — it rolls
  back by reloading the newest valid checkpoint through
  ``Fleet.load_check_point`` (PR-2 corrupt-fallback included) and raises
  :class:`errors.TrainingDivergedError` once the rollback budget is gone.

* **hung step** — the guard touches its :class:`health.Heartbeat` once
  per completed step; the launcher's ``--heartbeat_timeout`` watcher (or
  an in-process :class:`health.StepWatchdog` via `watchdog_timeout=`)
  notices when the beats stop.

* **preemption** — ``__enter__`` installs a SIGTERM handler that only
  sets a drain flag; the loop finishes its current step, then the guard
  writes a final ``Fleet.save_check_point`` and exits with
  :data:`health.PREEMPTION_EXIT_CODE`, which the launcher (and its
  ``--elastic`` restart accounting) treats as a clean exit.

Usage::

    with TrainGuard(exe, program=main, fleet=fleet,
                    checkpoint_dir="ckpts") as g:
        for epoch in range(epochs):
            for feed in loader:
                out = g.step(feed=feed, fetch_list=[loss])
                if out is None:
                    continue            # non-finite step was skipped
            g.train_status = TrainStatus(epoch)

Chaos seam: ``guard.step`` (``nonfinite`` poisons the feed, ``hang``
sleeps pre-step); metrics: ``resilience.bad_steps``, ``.rollbacks``,
``.preemptions``, ``guard.steps``.
"""

from __future__ import annotations

import os
import signal
import threading

from .health import (
    HEARTBEAT_DIR_ENV,
    HEARTBEAT_TIMEOUT_ENV,
    PREEMPTION_EXIT_CODE,
    Heartbeat,
    StepWatchdog,
)

__all__ = ["TrainGuard"]


def _device_copy(value):
    """A genuinely separate buffer: executor donation may invalidate the
    scope's old arrays on device backends, so a reference is not a
    snapshot. Stays on device for jax arrays (device-to-device copy)."""
    import jax.numpy as jnp
    import numpy as np

    if isinstance(value, np.ndarray):
        return value.copy()
    try:
        return jnp.array(value, copy=True)
    except Exception:
        return value


class TrainGuard:
    """Wrap a training step loop with numeric-health skip/rollback,
    heartbeat liveness, and preemption-graceful shutdown. See the module
    docstring for the policy; constructor knobs:

    executor, program:  what to run (program=None → default main program).
    fleet, checkpoint_dir, fs:  enable rollback (load_check_point) and the
        final preemption checkpoint (save_check_point).
    checkpointer:  a fleet.AsyncCheckpointer; the guard then (a) quiesces
        it before a rollback — cancel the queued snapshot, await the
        in-flight publish — so rollback always restores the newest
        COMMITTED checkpoint and never races a publish of the diverged
        state, (b) routes the SIGTERM drain checkpoint through it and
        awaits the publish before exiting 75 (never a half-published
        final checkpoint), and (c) wires the guard's heartbeat/watchdog
        into its publish liveness pulse. fleet/checkpoint_dir/fs default
        from the checkpointer when omitted.
    max_bad_steps:  consecutive non-finite steps before a rollback (or
        TrainingDivergedError when rollback is unavailable). Default 3.
    max_rollbacks:  rollback budget; the next rollback request past it
        raises TrainingDivergedError. Default 2.
    amp:  an OptimizerWithMixedPrecision to feed good/bad steps into.
    snapshot:  pre-step persistable snapshot enabling bad-step skip
        (default True; set False to trade skip-exactness for zero copy
        overhead — AMP's zeroed grads still no-op the update for fp16).
    heartbeat:  a health.Heartbeat, or None to auto-create when the
        launcher exported PADDLE_HEARTBEAT_DIR (else no beats).
    watchdog_timeout:  seconds to arm an in-process StepWatchdog
        (None → PADDLE_HEARTBEAT_TIMEOUT env when launched with a
        heartbeat dir, else off).
    exit_on_preempt:  raise SystemExit(PREEMPTION_EXIT_CODE) after the
        drain checkpoint (default True); False just sets `.preempted`.
    """

    def __init__(
        self,
        executor,
        program=None,
        scope=None,
        fleet=None,
        checkpoint_dir=None,
        fs=None,
        max_bad_steps=3,
        max_rollbacks=2,
        amp=None,
        snapshot=True,
        heartbeat=None,
        watchdog_timeout=None,
        exit_on_preempt=True,
        train_status=None,
        checkpointer=None,
        quiesce_timeout=600.0,
    ):
        self.executor = executor
        self.program = program
        self.scope = scope
        self.fleet = fleet
        self.checkpoint_dir = checkpoint_dir
        self.fs = fs
        self.checkpointer = checkpointer
        self.quiesce_timeout = quiesce_timeout
        if checkpointer is not None:
            # rollback + drain run against the checkpointer's store
            if self.fleet is None:
                self.fleet = checkpointer._fleet
            if self.checkpoint_dir is None:
                self.checkpoint_dir = checkpointer.path
            if self.fs is None:
                self.fs = checkpointer._fs
        self.max_bad_steps = int(max_bad_steps)
        self.max_rollbacks = int(max_rollbacks)
        self.amp = amp
        self.snapshot = snapshot
        self.exit_on_preempt = exit_on_preempt
        self.train_status = train_status

        self.steps = 0
        self.bad_steps = 0
        self.bad_streak = 0
        self.rollbacks = 0
        self.preempted = False
        self.draining = False

        if heartbeat is None and os.environ.get(HEARTBEAT_DIR_ENV):
            heartbeat = Heartbeat()
        self.heartbeat = heartbeat
        if watchdog_timeout is None and heartbeat is not None:
            env = os.environ.get(HEARTBEAT_TIMEOUT_ENV)
            watchdog_timeout = float(env) if env else None
        self._watchdog_timeout = watchdog_timeout
        self.watchdog = None
        self._old_sigterm = None
        self._finalized = False

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self):
        # SIGTERM → drain flag only: signal-safe, and the current step (a
        # device computation mid-flight) finishes instead of being torn
        if threading.current_thread() is threading.main_thread():
            self._old_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, self._on_sigterm)
        if self._watchdog_timeout:
            self.watchdog = StepWatchdog(
                self._watchdog_timeout, name="guard"
            ).start()
        if (
            self.checkpointer is not None
            and self.checkpointer._heartbeat is None
        ):
            # publish-time liveness: the publisher thread pulses the
            # guard's heartbeat + watchdog so a slow async publish never
            # reads as a hung step
            self.checkpointer._heartbeat = self._touch_liveness
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        if self._old_sigterm is not None:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None
        # drain requested right at loop end (no further step() call):
        # still honor the preemption contract on the way out
        if exc_type is None and self.draining and not self._finalized:
            self._finalize_preemption()
        return False

    def _on_sigterm(self, signum, frame):
        self.draining = True

    # -- exact-resume state ------------------------------------------------
    def state_dict(self):
        """The guard's recovery-policy position (step/bad-step counters and
        the spent rollback budget) for TrainStatus v2 capture — a resumed
        run must not get a fresh rollback budget for the same divergence."""
        return {
            "steps": self.steps,
            "bad_steps": self.bad_steps,
            "bad_streak": self.bad_streak,
            "rollbacks": self.rollbacks,
        }

    def load_state_dict(self, state):
        """Restore :meth:`state_dict`; empty/missing keys keep their
        defaults, so v1 (epoch-only) checkpoints restore cleanly."""
        if not state:
            return
        self.steps = int(state.get("steps", self.steps))
        self.bad_steps = int(state.get("bad_steps", self.bad_steps))
        self.bad_streak = int(state.get("bad_streak", self.bad_streak))
        self.rollbacks = int(state.get("rollbacks", self.rollbacks))

    # -- the guarded step --------------------------------------------------
    def step(self, feed=None, fetch_list=None, program=None,
             return_numpy=True, **run_kw):
        """Run one guarded training step. Returns the fetches, or None when
        the step was skipped (non-finite) or the loop is draining."""
        if self.draining:
            return self._finalize_preemption()
        from .. import observability as _obs
        from . import faults

        program = program if program is not None else self.program
        # chaos seam: "nonfinite" poisons the feed (a corrupted batch is
        # how real blow-ups arrive), "hang" sticks the step pre-beat
        feed = faults.corrupt_point("guard.step", feed)

        saved = self._snapshot(program) if self.snapshot else None
        fetches = self.executor.run(
            program, feed=feed, fetch_list=fetch_list,
            scope=self.scope, return_numpy=False, **run_kw,
        )
        good = self._all_finite(fetches)
        self.steps += 1
        _obs.add("guard.steps")

        if good:
            # no amp.note_step here: the in-graph update_loss_scaling op
            # already counted this good step — feeding it again would
            # double the scale-growth rate
            self.bad_streak = 0
            out = self._to_numpy(fetches) if return_numpy else list(fetches)
        else:
            self._skip_bad_step(saved)
            out = None
        self._beat()
        if self.draining:
            return self._finalize_preemption()
        return out

    def _skip_bad_step(self, saved):
        from .. import observability as _obs
        from ..errors import TrainingDivergedError

        self.bad_steps += 1
        self.bad_streak += 1
        _obs.add("resilience.bad_steps")
        if saved is not None:
            scope = self._scope()
            for name, value in saved.items():
                scope.set_var(name, value)
            # AFTER the restore (which reverted the in-graph automaton's
            # own decay), so exactly ONE decay survives the skip; with
            # snapshot=False the in-graph update_loss_scaling op already
            # decayed — feeding it again would double-decay
            if self.amp is not None:
                self.amp.note_step(False, scope=self.scope)
        if self.bad_streak < self.max_bad_steps:
            return
        # the same state keeps producing NaNs: skipping cannot help — roll
        # back to the newest valid checkpoint, if the budget allows.
        # has_check_point gates the load: load_check_point returns
        # TrainStatus(-1) BOTH for "nothing on disk" (cold start, scope
        # untouched) and for a real checkpoint whose status predates the
        # first epoch — only the former means rollback is impossible.
        if self.checkpointer is not None:
            # an async publish may be racing this rollback: drop the
            # queued snapshot (captured from the diverging timeline) and
            # await the in-flight publish, so load_check_point below sees
            # only committed checkpoints — never an uncommitted dir, and
            # never a later-landing publish of the state being abandoned.
            # BOUNDED wait: the publisher pulses this guard's own
            # heartbeat/watchdog, so an unbounded quiesce on a wedged
            # publish would hang forever while looking perfectly alive —
            # fail loudly instead
            if not self.checkpointer.quiesce(
                cancel_pending=True, timeout=self.quiesce_timeout
            ):
                from ..errors import ExecutionTimeoutError

                raise ExecutionTimeoutError(
                    "rollback blocked: the in-flight async checkpoint "
                    f"publish did not settle within {self.quiesce_timeout}"
                    "s (wedged upload?); refusing to wait forever behind "
                    "a liveness pulse that masks the stall"
                )
        if (
            self.fleet is not None and self.checkpoint_dir is not None
            and self.rollbacks < self.max_rollbacks
            and self.fleet.has_check_point(self.checkpoint_dir, fs=self.fs)
        ):
            self.train_status = self.fleet.load_check_point(
                self.executor, self.checkpoint_dir,
                main_program=self.program, fs=self.fs,
            )
            self.rollbacks += 1
            self.bad_streak = 0
            _obs.add("resilience.rollbacks")
            from ..observability import recorder as _recorder

            # flight-recorder trigger: the rollback rewinds the scope,
            # so the pre-rollback window (the diverging steps) is about
            # to become unreconstructable — dump it first
            _recorder.flight_dump("train_rollback", detail={
                "rollbacks": self.rollbacks, "bad_steps": self.bad_steps,
            })
            return
        if self.fleet is None or self.checkpoint_dir is None:
            why = "no fleet/checkpoint_dir configured for rollback"
        elif self.rollbacks >= self.max_rollbacks:
            why = f"rollback budget {self.max_rollbacks} exhausted"
        else:
            why = "no checkpoint available to roll back to"
        raise TrainingDivergedError(
            f"{self.bad_streak} consecutive non-finite steps and no "
            f"recovery left ({why}); total bad steps: {self.bad_steps}"
        )

    # -- preemption drain --------------------------------------------------
    def _finalize_preemption(self):
        """Final checkpoint + distinguished exit, once."""
        if self._finalized:
            if self.exit_on_preempt:
                raise SystemExit(PREEMPTION_EXIT_CODE)
            return None
        self._finalized = True
        self.preempted = True
        from .. import observability as _obs
        from ..observability import recorder as _recorder

        _obs.add("resilience.preemptions")
        # flight-recorder trigger: the SIGTERM drain ends in exit(75) —
        # capture the final window before the process goes away
        _recorder.flight_dump("preempt_drain", detail={
            "bad_steps": self.bad_steps, "rollbacks": self.rollbacks,
        })
        if self.fleet is not None and self.checkpoint_dir is not None:
            from ..fleet.collective import TrainStatus

            status = (
                self.train_status if self.train_status is not None
                else TrainStatus(-1)
            )
            if self.checkpointer is not None:
                # drain through the async pipeline, then AWAIT the
                # publish: exit 75 promises a committed final checkpoint,
                # never a half-published one (a publish failure surfaces
                # here and the preemption contract is abandoned loudly)
                self.checkpointer.save(status)
                self.checkpointer.wait()
            else:
                self.fleet.save_check_point(
                    self.executor, self.checkpoint_dir, status,
                    main_program=self.program, fs=self.fs,
                    heartbeat=self._touch_liveness,
                )
        if self.exit_on_preempt:
            raise SystemExit(PREEMPTION_EXIT_CODE)
        return None

    # -- helpers -----------------------------------------------------------
    def _scope(self):
        from ..framework.scope import global_scope

        return self.scope if self.scope is not None else global_scope()

    def _resolved_program(self, program=None):
        from ..framework.program import default_main_program

        program = program if program is not None else self.program
        program = program if program is not None else default_main_program()
        return getattr(program, "program", program)

    def _snapshot(self, program):
        """Pre-step copies of every scope-resident persistable of the
        program — restoring them IS the skip."""
        program = self._resolved_program(program)
        scope = self._scope()
        saved = {}
        for var in program.list_vars():
            if not getattr(var, "persistable", False):
                continue
            value = scope.find_var(var.name)
            if value is not None:
                saved[var.name] = _device_copy(value)
        return saved

    @staticmethod
    def _all_finite(fetches):
        """ONE fused on-device reduction over every inexact fetch."""
        import jax.numpy as jnp

        flags = [
            jnp.all(jnp.isfinite(f))
            for f in fetches
            if jnp.issubdtype(jnp.asarray(f).dtype, jnp.inexact)
        ]
        if not flags:
            return True
        return bool(jnp.stack(flags).all())

    @staticmethod
    def _to_numpy(fetches):
        import numpy as np

        return [np.asarray(f) for f in fetches]

    def _beat(self):
        if self.heartbeat is not None:
            self.heartbeat.beat()
        if self.watchdog is not None:
            self.watchdog.touch()

    def _touch_liveness(self):
        """Alive-but-same-step liveness for long checkpoint publishes:
        refresh the beat file's timestamp and the watchdog without
        advancing the per-step beat counter (safe from the publisher
        thread — Heartbeat and StepWatchdog are both lock-protected)."""
        if self.heartbeat is not None:
            self.heartbeat.touch()
        if self.watchdog is not None:
            self.watchdog.touch()
