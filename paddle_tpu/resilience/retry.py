"""Retry with exponential backoff, full jitter, timeouts, and a deadline.

Long-running multi-host training loops (PAPERS.md: arxiv 2004.13336's
weight-update sharding, EQuARX's collective layer) assume the host side
retries transient failures instead of dying; this is that discipline as a
library. Backoff follows the "full jitter" scheme (delay drawn uniformly
from [0, min(max_delay, base*2^attempt)]) so a pod of workers retrying the
same dead FS does not thunder back in lockstep.

Three call shapes share one policy object::

    @retry(max_attempts=5, name="checkpoint.publish")
    def publish(): ...

    retry(deadline=30.0).call(fs.upload, local, remote)

    for attempt in retry(max_attempts=4):
        with attempt:            # retryable exceptions inside the body are
            flaky_io()           # swallowed + slept on until attempts/
                                 # deadline run out, then re-raised

What counts as retryable is the `retry_on` classifier: an exception tuple
or a ``callable(exc) -> bool``. The default treats OSError /
ConnectionError / TimeoutError and the taxonomy's UnavailableError /
ExecutionTimeoutError / ResourceExhaustedError as transient, and honors an
explicit ``exc.retryable`` attribute either way (so
CheckpointCorruptionError — an OSError — stays fatal).

Counters through the PR-1 observability registry: ``resilience.retries``,
``resilience.giveups`` (plus ``.<name>``-suffixed variants when the policy
is named). ``clock``/``sleep``/``rng`` are injectable for deterministic
tests.
"""

from __future__ import annotations

import functools
import random
import threading
import time

__all__ = ["backoff_delay", "default_retryable", "retry"]


def default_retryable(exc):
    """Transient-failure classifier; `exc.retryable` overrides when set."""
    flag = getattr(exc, "retryable", None)
    if flag is not None:
        return bool(flag)
    from .. import errors

    return isinstance(
        exc,
        (
            ConnectionError,
            TimeoutError,
            OSError,
            errors.UnavailableError,
            errors.ExecutionTimeoutError,
            errors.ResourceExhaustedError,
        ),
    )


def backoff_delay(attempt, base_delay=0.1, max_delay=30.0, rng=None):
    """Delay before retry number `attempt` (1-based): full jitter over an
    exponentially growing cap. rng=None -> no jitter (the deterministic
    upper envelope, what the launcher's restart loop uses)."""
    cap = min(float(max_delay), float(base_delay) * (2.0 ** (attempt - 1)))
    return rng.uniform(0.0, cap) if rng is not None else cap


class _Attempt:
    """One try in the `for attempt in retry(...)` shape: a context manager
    that reports success/failure back to the policy."""

    __slots__ = ("_policy", "number")

    def __init__(self, policy, number):
        self._policy = policy
        self.number = number

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None:
            self._policy._succeeded = True
            return False
        if isinstance(exc, BaseException) and not isinstance(exc, Exception):
            return False  # KeyboardInterrupt etc.: never swallowed
        return self._policy._absorb(exc)  # True -> swallowed, will retry


class _RetryPolicy:
    def __init__(
        self,
        max_attempts=3,
        base_delay=0.1,
        max_delay=30.0,
        deadline=None,
        attempt_timeout=None,
        retry_on=default_retryable,
        name=None,
        sleep=time.sleep,
        clock=time.monotonic,
        rng=None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = None if deadline is None else float(deadline)
        self.attempt_timeout = (
            None if attempt_timeout is None else float(attempt_timeout)
        )
        self.retry_on = retry_on
        self.name = name
        self._sleep = sleep
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        # iterator-shape state
        self._attempt_no = 0
        self._succeeded = False
        self._t0 = None

    # -- classification ----------------------------------------------------
    def _is_retryable(self, exc):
        if callable(self.retry_on) and not isinstance(self.retry_on, type):
            return bool(self.retry_on(exc))
        return isinstance(exc, self.retry_on)

    def _count(self, what):
        from .. import observability as _obs

        _obs.add(f"resilience.{what}")
        if self.name:
            _obs.add(f"resilience.{what}.{self.name}")

    # -- core decision -----------------------------------------------------
    def _decide(self, exc, attempt_no, t0):
        """Seconds to back off before retrying, or None to give up (the
        giveup is counted here; the retry is counted by the caller once it
        actually commits — a runaway-attempt fence can still veto it).
        Pure of policy-instance state so concurrent `call`s (e.g. one
        decorated fetch shared by every dataloader worker thread) don't
        race."""
        if not self._is_retryable(exc):
            # a first-attempt non-retryable failure is an ordinary error,
            # not an abandoned retry budget — don't pollute the giveups
            # metric operators alert on
            if attempt_no > 1:
                self._count("giveups")
            return None
        if attempt_no >= self.max_attempts:
            self._count("giveups")
            return None
        delay = backoff_delay(
            attempt_no, self.base_delay, self.max_delay, self._rng
        )
        if (
            self.deadline is not None
            and (self._clock() - t0) + delay > self.deadline
        ):
            self._count("giveups")
            return None
        return delay

    def _absorb(self, exc):
        delay = self._decide(exc, self._attempt_no, self._t0)
        if delay is None:
            return False
        self._count("retries")
        if delay > 0:
            self._sleep(delay)
        return True

    # -- iterator shape ----------------------------------------------------
    def __iter__(self):
        self._attempt_no = 0
        self._succeeded = False
        self._t0 = self._clock()
        return self

    def __next__(self):
        if self._succeeded:
            raise StopIteration
        if self._attempt_no >= self.max_attempts:
            # only reachable when _absorb declined to swallow — the body's
            # exception already propagated, so this is a plain stop
            raise StopIteration
        self._attempt_no += 1
        return _Attempt(self, self._attempt_no)

    # -- callable shapes ---------------------------------------------------
    def _run_attempt(self, fn, args, kwargs, runaway):
        if self.attempt_timeout is None:
            return fn(*args, **kwargs)
        box = {}

        def target():
            try:
                box["value"] = fn(*args, **kwargs)
            except BaseException as e:  # re-raised on the caller thread
                box["error"] = e

        t = threading.Thread(
            target=target, daemon=True,
            name=f"retry-attempt-{self.name or 'anon'}",
        )
        t.start()
        t.join(self.attempt_timeout)
        if t.is_alive():
            from .. import errors

            # the runaway thread is abandoned (daemon): Python cannot kill
            # it, but the caller gets control back — the hang-proofing half
            # of the contract. call() refuses to start the next attempt
            # while this one is still running (no concurrent duplicates of
            # a possibly non-reentrant fn).
            runaway.append(t)
            raise errors.ExecutionTimeoutError(
                f"attempt exceeded {self.attempt_timeout}s"
                + (f" in {self.name!r}" if self.name else "")
            )
        if "error" in box:
            raise box["error"]
        return box["value"]

    def call(self, fn, *args, **kwargs):
        attempt_no, t0 = 0, self._clock()
        runaway = []  # timed-out attempt threads still running fn
        while True:
            attempt_no += 1
            try:
                return self._run_attempt(fn, args, kwargs, runaway)
            except Exception as exc:
                delay = self._decide(exc, attempt_no, t0)
                if delay is None:
                    raise
                if runaway:
                    # spend the backoff waiting for abandoned attempts; if
                    # any is STILL alive, give up rather than run two
                    # copies of fn concurrently (torn-write hazard)
                    deadline = self._clock() + max(delay, 0.0)
                    for t in runaway:
                        t.join(max(0.0, deadline - self._clock()))
                    if any(t.is_alive() for t in runaway):
                        self._count("giveups")
                        raise
                    runaway.clear()
                    self._count("retries")
                else:
                    self._count("retries")
                    if delay > 0:
                        self._sleep(delay)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapper.retry_policy = self
        return wrapper


def retry(**kwargs):
    """Build a retry policy; see module docstring for the three shapes."""
    return _RetryPolicy(**kwargs)
