"""Storage fault domain: disk pressure, retention GC, degradation ladder.

Every durable plane the repo has grown — numbered checkpoints
(fleet/collective.py), versioned publish bundles (fleet/publish.py),
telemetry journals and flight bundles (observability/timeline.py,
recorder.py), heartbeat files (health.py) — assumed a healthy volume
with infinite space: there was not a single ``statvfs``/ENOSPC path in
the tree, so a filling disk was the one failure mode that disabled *all*
recovery machinery at once. This module is the shared fault domain:

* :class:`StorageMonitor` — per-root free-space + write-latency probes
  on the health-poll cadence, published as ``storage.free_bytes.<root>``
  / ``storage.write_latency.<root>`` gauges, with a hysteresis-latched
  pressure level per root (and overall): OK → SOFT → HARD → CRITICAL.
  Escalation is immediate (a filling disk gets no grace); de-escalation
  requires free bytes to clear the triggering threshold by a ``rearm``
  margin, so a volume hovering at a boundary cannot flap the ladder. A
  root may carry a ``budget_bytes`` synthetic volume (free = budget −
  bytes used under the root) so tests and CI exercise every rung by
  filling a BUDGET, never the real disk — and ``io.py``'s preflight
  consults the same budget through :func:`free_bytes`.
* :class:`RetentionManager` — cross-plane GC with per-plane policies,
  invoked under pressure (or on a cadence): checkpoint rotation against
  a bytes budget (sparing delta-chain ancestors of survivors), publish
  bundle pruning (sparing ``resolve_chain`` ancestors of the newest
  eligible version AND every version a live subscriber's heartbeat
  still stamps — no reader's chain is ever cut), rotated telemetry
  shards of dead processes, and aged flight bundles. Deletion is
  crash-safe marker-first (the repo's established discipline: the
  commit record is unlinked BEFORE the payload, so a dir stops existing
  to readers before its bytes disappear — either crash half is
  recoverable by the CRC-verify/skip-broken load machinery) and
  journaled as ``storage.gc_bytes_freed`` (+ per-plane counters and a
  ``storage.gc`` actions table).
* :class:`StoragePressureController` — the degradation ladder walked
  beside ``serving.brownout.BrownoutController``, shedding the cheapest
  durability first: SOFT forces compressed, delta-only checkpoints and
  aggressive telemetry rotation; HARD freezes model publishes (the
  PR-18 freeze rung), drops telemetry journaling to the in-memory
  registry only (the flight recorder keeps *sampling*, stops *writing*)
  and runs emergency GC; CRITICAL refuses new checkpoint/publish writes
  with a typed :class:`~paddle_tpu.errors.StorageExhaustedError` and
  takes ONE flight dump — serving keeps running on the weights it has.
  Every rung re-arms downward through the monitor's hysteresis;
  transitions count ``storage.escalations`` / ``storage.recoveries``.

The write-side contract lives in ``io.py``: atomic writers preflight an
``estimated_size`` against :func:`free_bytes`, map ENOSPC/EDQUOT to
``StorageExhaustedError`` with the temp already unlinked, and expose the
``fault_point("fs.write")`` chaos seam (kinds ``enospc`` / ``slow``).
:func:`require_writable` is the loose coupling back into the writers:
checkpoint and publish entry points call it and get the CRITICAL-rung
refusal without holding a controller reference.

Env knobs: ``PADDLE_TPU_STORAGE_SOFT_BYTES`` (default 1 GiB),
``PADDLE_TPU_STORAGE_HARD_BYTES`` (256 MiB),
``PADDLE_TPU_STORAGE_CRITICAL_BYTES`` (64 MiB),
``PADDLE_TPU_STORAGE_REARM`` (de-escalation margin factor, default
1.25). README §Storage fault domain documents the full catalog.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time

__all__ = [
    "CRITICAL",
    "CRITICAL_BYTES_ENV",
    "HARD",
    "HARD_BYTES_ENV",
    "LEVEL_NAMES",
    "OK",
    "REARM_ENV",
    "RetentionManager",
    "SOFT",
    "SOFT_BYTES_ENV",
    "StorageMonitor",
    "StoragePressureController",
    "current_monitor",
    "free_bytes",
    "install",
    "require_writable",
    "uninstall",
]

# -- pressure levels ---------------------------------------------------------
OK, SOFT, HARD, CRITICAL = 0, 1, 2, 3
LEVEL_NAMES = {OK: "ok", SOFT: "soft", HARD: "hard", CRITICAL: "critical"}

SOFT_BYTES_ENV = "PADDLE_TPU_STORAGE_SOFT_BYTES"
HARD_BYTES_ENV = "PADDLE_TPU_STORAGE_HARD_BYTES"
CRITICAL_BYTES_ENV = "PADDLE_TPU_STORAGE_CRITICAL_BYTES"
REARM_ENV = "PADDLE_TPU_STORAGE_REARM"

_DEFAULT_SOFT = 1 << 30        # 1 GiB
_DEFAULT_HARD = 256 << 20      # 256 MiB
_DEFAULT_CRITICAL = 64 << 20   # 64 MiB
_DEFAULT_REARM = 1.25


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _du(path):
    """Bytes used under `path` (os.walk; unreadable entries skipped)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _statvfs_free(path):
    try:
        st = os.statvfs(path)
        return st.f_bavail * st.f_frsize
    except (OSError, AttributeError):
        return None


# -- the monitor -------------------------------------------------------------
class StorageMonitor:
    """Per-root free-space / write-latency probes with a latched level.

    ``add_root(name, path)`` registers a durable root (conventionally
    ``"checkpoint"``, ``"publish"``, ``"telemetry"``, ``"heartbeat"`` —
    the name keys the per-root gauges and the plane names
    :func:`require_writable` checks). ``poll()`` probes every root,
    publishes the gauges, advances the hysteresis latches, and returns
    the poll summary (including level-change events a Watcher turns into
    ``disk_pressure`` findings). ``install()`` makes this monitor the
    process-global one the io.py preflight and ``require_writable``
    consult.
    """

    def __init__(self, soft_bytes=None, hard_bytes=None,
                 critical_bytes=None, rearm=None, probe=True,
                 probe_bytes=4096):
        soft = (_env_int(SOFT_BYTES_ENV, _DEFAULT_SOFT)
                if soft_bytes is None else int(soft_bytes))
        hard = (_env_int(HARD_BYTES_ENV, _DEFAULT_HARD)
                if hard_bytes is None else int(hard_bytes))
        crit = (_env_int(CRITICAL_BYTES_ENV, _DEFAULT_CRITICAL)
                if critical_bytes is None else int(critical_bytes))
        if not crit <= hard <= soft:
            from ..errors import InvalidArgumentError

            raise InvalidArgumentError(
                "StorageMonitor thresholds must satisfy critical <= hard "
                f"<= soft, got {crit} / {hard} / {soft}"
            )
        self.thresholds = {SOFT: soft, HARD: hard, CRITICAL: crit}
        self.rearm = (_env_float(REARM_ENV, _DEFAULT_REARM)
                      if rearm is None else float(rearm))
        self.probe = bool(probe)
        self._probe_payload = b"\0" * int(probe_bytes)
        self.roots = {}
        self.level = OK
        self.polls = 0
        self._lock = threading.Lock()

    # -- roots -------------------------------------------------------------
    def add_root(self, name, path, budget_bytes=None):
        """Register a durable root; returns self (chainable). A
        `budget_bytes` root reports ``budget - du(path)`` as its free
        bytes — the synthetic volume tests/CI fill instead of the disk."""
        path = os.path.abspath(os.fspath(path))
        os.makedirs(path, exist_ok=True)
        with self._lock:
            self.roots[str(name)] = {
                "path": path,
                "budget": None if budget_bytes is None else int(budget_bytes),
                "level": OK,
                "free": None,
                "latency": None,
            }
        return self

    def install(self):
        """Make this the process-global monitor (see :func:`install`)."""
        install(self)
        return self

    # -- probes ------------------------------------------------------------
    def _free_of(self, root):
        if root["budget"] is not None:
            return max(0, root["budget"] - _du(root["path"]))
        return _statvfs_free(root["path"])

    def _probe_latency(self, root):
        """Timed tiny durable write into the root (through the full
        io._atomic_write contract, fs.write seam included) — what the
        ``storage.write_latency.<root>`` gauge reports. A failed probe
        still reports its elapsed time and counts
        ``storage.probe_failures``; it never raises."""
        from .. import io as _io
        from .. import observability as _obs

        target = os.path.join(root["path"], ".storage_probe")
        t0 = time.perf_counter()
        try:
            _io._atomic_write(target, lambda f: f.write(self._probe_payload))
        except Exception:
            _obs.add("storage.probe_failures")
        finally:
            try:
                os.unlink(target)
            except OSError:
                pass
        return time.perf_counter() - t0

    def _raw_level(self, free):
        if free is None:
            return OK
        if free < self.thresholds[CRITICAL]:
            return CRITICAL
        if free < self.thresholds[HARD]:
            return HARD
        if free < self.thresholds[SOFT]:
            return SOFT
        return OK

    def _latch(self, root, free):
        """Hysteresis: escalate immediately to the raw level; de-escalate
        one rung at a time, only once free clears the current rung's
        threshold by the re-arm margin."""
        lvl = root["level"]
        raw = self._raw_level(free)
        if raw > lvl:
            lvl = raw
        else:
            while lvl > raw and free is not None and (
                free >= self.thresholds[lvl] * self.rearm
            ):
                lvl -= 1
        root["level"] = lvl
        return lvl

    # -- the poll ----------------------------------------------------------
    def poll(self):
        """Probe every root; returns ``{"level", "previous", "events",
        "roots"}`` where events is ``[(root_name, old_level, new_level),
        ...]`` for roots whose latched level changed this poll."""
        from .. import observability as _obs

        events = []
        with self._lock:
            self.polls += 1
            for name, root in self.roots.items():
                free = self._free_of(root)
                root["free"] = free
                if self.probe:
                    root["latency"] = self._probe_latency(root)
                    _obs.set_gauge(
                        f"storage.write_latency.{name}", root["latency"]
                    )
                if free is not None:
                    _obs.set_gauge(f"storage.free_bytes.{name}", float(free))
                old = root["level"]
                new = self._latch(root, free)
                _obs.set_gauge(f"storage.pressure.{name}", float(new))
                if new != old:
                    events.append((name, old, new))
            previous = self.level
            self.level = max(
                [r["level"] for r in self.roots.values()], default=OK
            )
            overall = self.level
            snapshot = {
                name: dict(root) for name, root in self.roots.items()
            }
        _obs.set_gauge("storage.pressure", float(overall))
        _obs.add("storage.polls")
        if overall > previous:
            _obs.add("storage.escalations")
        elif overall < previous:
            _obs.add("storage.recoveries")
        return {
            "level": overall,
            "previous": previous,
            "events": events,
            "roots": snapshot,
        }

    def level_of(self, name=None):
        """The latched level of one root (overall when `name` is None or
        unregistered) — what :func:`require_writable` checks."""
        with self._lock:
            if name is not None and name in self.roots:
                return self.roots[name]["level"]
            return self.level

    def free_of(self, name):
        """Last-polled free bytes of one root, or None."""
        with self._lock:
            root = self.roots.get(name)
            return None if root is None else root["free"]


# -- process-global wiring ---------------------------------------------------
_monitor: StorageMonitor | None = None


def install(monitor):
    """Make `monitor` the process-global storage monitor: io.py's
    preflight resolves budget roots through it (:func:`free_bytes`) and
    the checkpoint/publish writers' :func:`require_writable` gate reads
    its latched level."""
    global _monitor
    _monitor = monitor
    return monitor


def uninstall():
    global _monitor
    _monitor = None


def current_monitor():
    return _monitor


def free_bytes(path):
    """Free bytes available for a write under `path`: the installed
    monitor's budget when a byte-budgeted root covers the path (tests/CI
    fill budgets, not disks), else statvfs; None when unknowable."""
    mon = _monitor
    path = os.path.abspath(os.fspath(path))
    if mon is not None:
        with mon._lock:
            roots = [
                (r["path"], r["budget"]) for r in mon.roots.values()
                if r["budget"] is not None
            ]
        for rpath, budget in roots:
            if path == rpath or path.startswith(rpath + os.sep):
                return max(0, budget - _du(rpath))
    return _statvfs_free(path)


def require_writable(plane):
    """The CRITICAL-rung refusal, loosely coupled: checkpoint and publish
    entry points call this with their plane name ("checkpoint" /
    "publish") and get a typed :class:`StorageExhaustedError` when the
    installed monitor has that root (or the fleet overall) latched at
    CRITICAL. A no-op when no monitor is installed — the default path
    costs one global read."""
    mon = _monitor
    if mon is None:
        return
    level = mon.level_of(plane)
    if level >= CRITICAL:
        from .. import observability as _obs
        from ..errors import StorageExhaustedError

        _obs.add("storage.writes_refused")
        _obs.add(f"storage.writes_refused.{plane}")
        raise StorageExhaustedError(
            f"storage pressure is CRITICAL: refusing new {plane} writes "
            "until retention GC (or an operator) frees space — serving "
            "continues on the state already published"
        )


# -- retention GC ------------------------------------------------------------
_CKPT_PREFIX = "__paddle_checkpoint__"
_FLIGHT_TRIGGER_RE = re.compile(r"^flight_rank\d+\..+\.json$")


class RetentionManager:
    """Cross-plane retention GC: per-plane policies, one ``collect()``.

    Register planes with the ``add_*_plane`` methods; each policy is a
    callable returning bytes freed. ``collect()`` runs every policy,
    sums the reclaim into ``storage.gc_bytes_freed`` (+ per-plane
    counters), bumps ``storage.gc_runs``, and mirrors the per-plane
    actions into the journaled ``storage.gc`` table so
    ``tools/fleet_report.py`` renders GC history offline. Policies never
    raise out of ``collect()`` — a broken plane must not stop the others
    from freeing space (failures count ``storage.gc_failures``).
    """

    def __init__(self):
        self._policies = []   # (plane name, callable(emergency) -> bytes)
        self._actions = []
        self._lock = threading.Lock()

    def add_plane(self, name, fn):
        """Register a custom policy: ``fn(emergency: bool) -> bytes``."""
        with self._lock:
            self._policies.append((str(name), fn))
        return self

    # -- built-in plane policies -------------------------------------------
    def add_checkpoint_plane(self, path, budget_bytes, keep_min=1):
        """Checkpoint rotation against a BYTES budget: oldest first, but
        a checkpoint some survivor's delta chain still reaches is spared
        (the PR-12 rotation discipline), as are the `keep_min` newest.
        Marker-first deletes: ``commit.json`` unlinks before the payload,
        so a crash mid-GC leaves an incomplete dir the loader skips."""
        return self.add_plane(
            "checkpoint",
            lambda emergency=False: _gc_checkpoints(
                path, int(budget_bytes), keep_min=int(keep_min)
            ),
        )

    def add_publish_plane(self, publish_dir, keep=2, heartbeat_dir=None,
                          protect=()):
        """Publish-bundle pruning that can never cut a reader's chain:
        the ``resolve_chain`` ancestors of the newest eligible version,
        of every version a live subscriber's heartbeat stamps
        (``model_version``), and of every explicitly protected version
        all survive; everything older than the `keep` newest committed
        versions outside that set is pruned (commit record first)."""
        return self.add_plane(
            "publish",
            lambda emergency=False: _gc_publish(
                publish_dir, keep=int(keep), heartbeat_dir=heartbeat_dir,
                protect=protect,
            ),
        )

    def add_telemetry_plane(self, directory, dead_after_s=300.0):
        """Rotated (``.jsonl.1``) telemetry shards whose writer stopped:
        a live publisher re-rotates its shard continuously, so a rotated
        shard untouched for `dead_after_s` belongs to a dead process and
        its history is already replayable from the current shard's base
        record. Emergency GC sweeps rotated shards regardless of age."""
        return self.add_plane(
            "telemetry",
            lambda emergency=False: _gc_telemetry(
                directory, dead_after_s=float(dead_after_s),
                emergency=emergency,
            ),
        )

    def add_flight_plane(self, directory, keep=None, max_age_s=3600.0):
        """Aged flight TRIGGER bundles (the black box
        ``flight_rank{K}.json`` is never touched): keep the newest
        `keep` (default ``PADDLE_TPU_FLIGHT_KEEP``), drop any older than
        `max_age_s`."""
        return self.add_plane(
            "flight",
            lambda emergency=False: _gc_flight(
                directory, keep=keep, max_age_s=max_age_s,
            ),
        )

    # -- collection --------------------------------------------------------
    def collect(self, emergency=False):
        """Run every plane policy; returns total bytes freed."""
        from .. import observability as _obs

        total = 0
        with self._lock:
            policies = list(self._policies)
        for name, fn in policies:
            try:
                freed = int(fn(emergency) or 0)
            except Exception:
                _obs.add("storage.gc_failures")
                continue
            total += freed
            if freed:
                _obs.add(f"storage.gc_bytes_freed.{name}", freed)
            with self._lock:
                self._actions.append({
                    "plane": name, "freed": freed, "t": time.time(),
                    "emergency": bool(emergency),
                })
                del self._actions[:-32]
                table = list(self._actions)
        _obs.add("storage.gc_runs")
        if total:
            _obs.add("storage.gc_bytes_freed", total)
        _obs.set_gauge("storage.gc_last_bytes_freed", float(total))
        _obs.set_table("storage.gc", {"actions": table})
        return total


def _delete_marker_first(dirpath, marker):
    """Crash-safe dir delete: the commit marker unlinks (and the dir
    fsyncs) BEFORE the payload disappears, so readers stop seeing the
    version before its bytes go — either crash half leaves a skippable,
    not a torn, dir. Returns bytes freed."""
    from .. import io as _io

    size = _du(dirpath)
    try:
        os.unlink(os.path.join(dirpath, marker))
        _io._fsync_dir(dirpath)
    except OSError:
        pass
    shutil.rmtree(dirpath, ignore_errors=True)
    return size


def _gc_checkpoints(path, budget_bytes, keep_min=1):
    try:
        entries = os.listdir(path)
    except OSError:
        return 0
    nos = sorted(
        int(e[len(_CKPT_PREFIX):]) for e in entries
        if e.startswith(_CKPT_PREFIX) and e[len(_CKPT_PREFIX):].isdigit()
    )
    if not nos:
        return 0
    dirs = {n: os.path.join(path, f"{_CKPT_PREFIX}{n}") for n in nos}
    sizes = {n: _du(dirs[n]) for n in nos}

    def chain_of(n):
        """n plus every delta-chain ancestor it folds over."""
        seen = set()
        cur = n
        while cur is not None and cur not in seen and cur in dirs:
            seen.add(cur)
            try:
                with open(os.path.join(dirs[cur], "delta.json")) as f:
                    cur = int(json.load(f)["base_checkpoint_no"])
            except (OSError, ValueError, KeyError, TypeError):
                cur = None
        return seen

    survivors = list(nos)
    total = sum(sizes.values())
    freed = 0
    keep_min = max(1, int(keep_min))
    while total > budget_bytes and len(survivors) > keep_min:
        required = set()
        for s in survivors:
            required |= chain_of(s) - {s}
        required.update(survivors[-keep_min:])
        cand = next((n for n in survivors if n not in required), None)
        if cand is None:
            break  # every remaining checkpoint anchors a survivor's chain
        reclaimed = _delete_marker_first(dirs[cand], "commit.json")
        freed += reclaimed
        total -= sizes[cand]
        survivors.remove(cand)
    return freed


def _gc_publish(publish_dir, keep=2, heartbeat_dir=None, protect=()):
    from ..fleet import publish as _pub

    committed = _pub.committed_versions(publish_dir)
    keep = max(1, int(keep))
    if len(committed) <= keep:
        return 0
    targets = set(committed[-keep:])
    targets.update(int(v) for v in protect)
    newest = _pub.latest_version(publish_dir)
    if newest is not None:
        targets.add(newest)
    if heartbeat_dir and os.path.isdir(heartbeat_dir):
        from .health import read_beat

        # the live-subscriber fence: every worker stamps the version it
        # serves into its beat file, so the set of versions someone may
        # still fold a chain for is discoverable from disk alone
        for fn in os.listdir(heartbeat_dir):
            if not fn.startswith("hb_rank") or ".tmp." in fn:
                continue
            beat = read_beat(os.path.join(heartbeat_dir, fn))
            if beat and beat.get("model_version") is not None:
                try:
                    targets.add(int(beat["model_version"]))
                except (TypeError, ValueError):
                    pass
    protected = set(targets)
    for v in targets:
        try:
            protected.update(_pub.resolve_chain(publish_dir, v))
        except Exception:
            pass  # already-broken chain: nothing more to protect
    freed = 0
    for v in committed:
        if v in protected:
            continue
        freed += _delete_marker_first(
            _pub.version_dir(publish_dir, v), _pub.COMMIT_NAME
        )
    return freed


def _gc_telemetry(directory, dead_after_s=300.0, emergency=False):
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    now = time.time()
    freed = 0
    for fn in entries:
        if not (fn.startswith("telemetry_rank") and fn.endswith(".jsonl.1")):
            continue
        p = os.path.join(directory, fn)
        try:
            if not emergency and now - os.path.getmtime(p) <= dead_after_s:
                continue
            size = os.path.getsize(p)
            os.unlink(p)
            freed += size
        except OSError:
            continue
    return freed


def _gc_flight(directory, keep=None, max_age_s=3600.0):
    from ..observability import recorder as _recorder

    if keep is None:
        keep = _recorder.flight_keep()
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    dumps = []
    for fn in entries:
        if not _FLIGHT_TRIGGER_RE.match(fn):
            continue
        p = os.path.join(directory, fn)
        try:
            dumps.append((os.path.getmtime(p), os.path.getsize(p), p))
        except OSError:
            continue
    dumps.sort(reverse=True)  # newest first
    now = time.time()
    freed = 0
    for i, (mtime, size, p) in enumerate(dumps):
        aged = max_age_s is not None and now - mtime > float(max_age_s)
        if i < int(keep) and not aged:
            continue
        try:
            os.unlink(p)
            freed += size
        except OSError:
            continue
    return freed


# -- the degradation ladder --------------------------------------------------
class StoragePressureController:
    """Walk the storage degradation ladder off the monitor's level.

    ======== ==========================================================
    level    behavior
    ======== ==========================================================
    OK       full durability (every knob at its configured value)
    SOFT     checkpoints forced compressed + delta-only
             (``AsyncCheckpointer.set_storage_degraded``); telemetry
             rotation cap shrunk to ``soft_journal_bytes`` — the
             journal stays live but bounded tight
    HARD     model publishes frozen (``publish_control.freeze()`` — a
             ``RolloutController`` or ``ModelPublisher``); telemetry
             journaling paused (the in-memory registry ring is the only
             telemetry); flight recorder keeps sampling, stops disk
             publishing; emergency GC runs (re-runs at most every
             ``gc_interval`` while pressure persists)
    CRITICAL everything above, plus the write gate: checkpoint/publish
             entry points consulting :func:`require_writable` refuse
             typed; ONE flight dump (trigger ``disk_pressure``) records
             the window — serving keeps running
    ======== ==========================================================

    Rung ordering is shed-cheapest-first, mirroring brownout: telemetry
    breadth goes before model freshness, model freshness before
    checkpoint durability, and serving availability is never traded.
    Every rung re-applies idempotently each poll and unwinds on the
    monitor's hysteresis-gated recovery.
    """

    def __init__(self, monitor, retention=None, checkpointer=None,
                 publish_control=None, telemetry=None, recorder=None,
                 interval=2.0, gc_interval=5.0,
                 soft_journal_bytes=1 << 20):
        self.monitor = monitor
        self.retention = retention
        self.checkpointer = checkpointer
        self.publish_control = publish_control
        self.telemetry = telemetry
        self.recorder = recorder
        self.interval = float(interval)
        self.gc_interval = float(gc_interval)
        self.soft_journal_bytes = int(soft_journal_bytes)
        self.level = OK
        self._journal_bytes_orig = (
            None if telemetry is None else int(telemetry.max_bytes)
        )
        self._last_gc = None
        self._dumped_critical = False
        self._stop = threading.Event()
        self._thread = None

    # -- decision + application --------------------------------------------
    def poll(self):
        """One monitor poll + idempotent rung application; returns the
        ladder level."""
        info = self.monitor.poll()
        self.level = info["level"]
        self._apply(self.level)
        return self.level

    def _apply(self, level):
        from .. import observability as _obs

        # SOFT rung: cheapest durability first — smaller checkpoints,
        # tighter journal, nothing frozen yet
        if self.checkpointer is not None:
            try:
                self.checkpointer.set_storage_degraded(level >= SOFT)
            except Exception:
                pass  # degraded checkpointing must not break degradation
        if self.telemetry is not None:
            try:
                self.telemetry.max_bytes = (
                    min(self._journal_bytes_orig, self.soft_journal_bytes)
                    if level >= SOFT else self._journal_bytes_orig
                )
                if level >= HARD:
                    self.telemetry.pause()
                else:
                    self.telemetry.resume()
            except Exception:
                pass
        # HARD rung: freeze model freshness, stop all optional disk
        # writers, reclaim space
        if self.publish_control is not None:
            try:
                if level >= HARD:
                    try:
                        self.publish_control.freeze(reason="disk_pressure")
                    except TypeError:
                        self.publish_control.freeze()
                else:
                    self.publish_control.unfreeze()
            except Exception:
                pass
        if self.recorder is not None:
            try:
                if level >= HARD:
                    self.recorder.suspend_disk()
                else:
                    self.recorder.resume_disk()
            except Exception:
                pass
        if level >= HARD and self.retention is not None:
            now = time.monotonic()
            if self._last_gc is None or (
                now - self._last_gc >= self.gc_interval
            ):
                self._last_gc = now
                try:
                    self.retention.collect(emergency=True)
                except Exception:
                    pass
        if level < HARD:
            self._last_gc = None
        # CRITICAL rung: the refusal gate lives in require_writable (the
        # monitor's latched level IS the gate); here: one post-mortem
        if level >= CRITICAL:
            if not self._dumped_critical:
                self._dumped_critical = True
                from ..observability.recorder import flight_dump

                flight_dump("disk_pressure", detail={
                    "level": LEVEL_NAMES[level],
                    "roots": {
                        name: root["free"]
                        for name, root in self.monitor.poll()["roots"].items()
                    },
                })
        else:
            self._dumped_critical = False
        _obs.set_gauge("storage.ladder_level", float(level))

    # -- live wiring -------------------------------------------------------
    def start(self):
        """Poll on a daemon thread every ``interval`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="storage-pressure"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception:
                pass  # a broken poll must not kill the controller thread
