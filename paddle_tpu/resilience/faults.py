"""Deterministic fault injection (chaos seams for the resilience layer).

A *site* is a named seam in production code (``fault_point("io.save")``)
that is free when nothing is armed. Arming a site — programmatically via
``inject()`` or from the ``PADDLE_TPU_FAULT_INJECT`` environment variable —
makes the seam raise a typed, usually-retryable exception with a
deterministic, seeded pattern, so chaos runs are reproducible and CI can
assert exact behavior (the reference stack tested fault tolerance the
ad-hoc way: kill -9 in shell scripts; a seeded in-process registry lets the
same scenarios run inside pytest).

Env syntax (comma/semicolon-separated specs)::

    PADDLE_TPU_FAULT_INJECT="site:kind[:prob[:seed[:max_fires]]][,spec...]"
    # e.g. "io.save:io:1.0:0:1,dataloader.fetch:unavailable:0.5:42"

``kind`` selects the exception: ``io`` (ExternalError, an OSError),
``unavailable`` (UnavailableError), ``timeout`` (ExecutionTimeoutError) —
all retryable — ``corrupt`` (CheckpointCorruptionError, NOT retryable),
and ``enospc`` (a plain ``OSError`` carrying ``errno.ENOSPC`` — exactly
what a full volume raises, so the io.py atomic writers' ENOSPC→
``StorageExhaustedError`` mapping path is what gets exercised, not
bypassed). Three kinds misbehave instead of raising: ``hang`` sleeps at
the seam for ``PADDLE_TPU_FAULT_HANG_SECONDS`` (default 3600 — "stuck",
from a watchdog's point of view), ``slow`` sleeps for
``PADDLE_TPU_FAULT_SLOW_SECONDS`` (default 0.25 — a degraded disk, not a
dead one: the write completes, the latency probe sees it), and
``nonfinite`` poisons the value passing through a :func:`corrupt_point`
seam with NaNs (at a plain :func:`fault_point` it degrades to raising
NonFiniteError).
``prob`` in [0,1] is drawn from a per-spec ``random.Random(seed)``; the
optional ``max_fires`` caps total fires (prob=1 + max_fires=1 = "fail
exactly once, then heal" — the deterministic shape chaos CI wants).

Wired seams: ``io.save`` / ``io.load`` (io.py), ``fs.upload`` /
``fs.download`` / ``fs.mv`` / ``fs.delete`` / ``fs.mkdir`` /
``fs.list_dirs`` (LocalFS — the last two cover the directory-scan prelude
of a checkpoint save, which Fleet retries under ``checkpoint.prepare``),
``fs.hadoop`` (HadoopFS shell-outs), ``dataloader.fetch`` (worker batch
fetch),
``collective.dispatch`` (trace-time collective emission),
``guard.step`` (TrainGuard pre-step: corrupt_point over the feed, so
``nonfinite`` fabricates a divergence and ``hang`` a stuck step),
``health.beat`` (Heartbeat.beat: ``hang`` makes the beat never land, what
a stalled rank looks like to the launcher),
``checkpoint.snapshot`` (the async checkpointer's device→host staging
stage, on the step-loop thread — retried under the ``checkpoint.snapshot``
policy; ``hang`` stalls the step exactly where a slow host copy would)
``checkpoint.publish`` (inside the background publisher's — and the
sync save's — write-and-publish body, within the ``checkpoint.save`` /
``checkpoint.shard`` retry scope, so raising kinds heal and ``hang``
deterministically wedges a publish mid-flight for SIGKILL chaos),
and ``serving.dispatch`` (the serving router's batch-dispatch boundary,
alongside the existing ``serving.ingest`` admission seam: inside a
``ReplicaSet`` the seam fires per replica attempt under the breaker +
attempt-timeout machinery — raising kinds read as replica failures and
``hang`` as a wedged executable the timeout converts to a typed error,
so chaos exercises the exact failover path; a per-replica
``serving.dispatch.<name>`` seam rides along for targeted replica
kills, and on a plain single-runner endpoint a raising kind fails the
batch typed while ``hang`` wedges the scheduler — the failure mode the
ReplicaSet exists to bound). The process-fleet worker protocol adds
``serving.transport.send`` / ``serving.transport.recv`` (fired inside
``serving.worker.send_msg``/``recv_msg`` on BOTH ends of the
length-prefixed socket stream): raising kinds surface as a typed
``TransportError`` the fleet's breaker + exactly-once failover absorb,
and ``hang`` wedges one wire call until the attempt-timeout watchdog
types it — transport chaos without killing any process. The live-publish
plane adds ``publish.commit`` (inside ``ModelPublisher.publish`` AFTER
the payload write but BEFORE the ``commit.json`` visibility barrier:
raising kinds leave an invisible carcass the next publish reclaims, and
``hang`` holds the bundle uncommitted — the SIGKILL-mid-publish window)
and ``publish.apply`` (inside a ``ModelSubscriber``'s scope mutation,
between the pre-apply snapshot and the version flip: raising kinds
exercise the torn-apply fence — the snapshot restores and the version
gauge never moves — and ``hang`` wedges a worker mid-apply for the
respawn-consistency chaos stage). The storage fault domain adds
``fs.write`` (inside ``io._atomic_write``, AFTER the temp file exists
but BEFORE any byte lands, so every fired kind exercises the
unlink-on-failure path: ``enospc`` is the disk filling mid-write —
mapped to a typed ``StorageExhaustedError`` by the writer — and
``slow`` a degraded volume the StorageMonitor's write-latency probe
measures). The catalog is documented in README §Resilience.
"""

from __future__ import annotations

import os
import random
import threading
import time

__all__ = [
    "FAULT_ENV_VAR",
    "HANG_SECONDS_ENV",
    "SLOW_SECONDS_ENV",
    "FaultSpec",
    "clear",
    "corrupt_point",
    "fault_point",
    "inject",
    "parse_spec",
    "reload_env",
    "specs",
]

FAULT_ENV_VAR = "PADDLE_TPU_FAULT_INJECT"
HANG_SECONDS_ENV = "PADDLE_TPU_FAULT_HANG_SECONDS"
SLOW_SECONDS_ENV = "PADDLE_TPU_FAULT_SLOW_SECONDS"

_KINDS = ("io", "unavailable", "timeout", "corrupt", "enospc", "hang",
          "slow", "nonfinite")


def _make_error(kind, site):
    from .. import errors

    msg = f"injected {kind!r} fault at site {site!r}"
    if kind == "io":
        return errors.ExternalError(msg)
    if kind == "unavailable":
        return errors.UnavailableError(msg)
    if kind == "timeout":
        return errors.ExecutionTimeoutError(msg)
    if kind == "corrupt":
        return errors.CheckpointCorruptionError(msg)
    if kind == "enospc":
        # a RAW OSError with the real errno, not the typed
        # StorageExhaustedError: the production mapping (io._atomic_write
        # catching ENOSPC/EDQUOT and raising the typed error with the
        # temp unlinked) is exactly what the injection must exercise
        import errno

        return OSError(errno.ENOSPC, f"{os.strerror(errno.ENOSPC)} ({msg})")
    if kind == "nonfinite":
        return errors.NonFiniteError(msg)
    raise ValueError(f"unknown fault kind {kind!r} (one of {_KINDS})")


def _hang_seconds():
    try:
        return float(os.environ.get(HANG_SECONDS_ENV, "3600"))
    except ValueError:
        return 3600.0


def _slow_seconds():
    try:
        return float(os.environ.get(SLOW_SECONDS_ENV, "0.25"))
    except ValueError:
        return 0.25


def _poison(value):
    """NaN-fill every inexact array inside `value` (dict/list/tuple walked
    recursively; non-float leaves pass through untouched)."""
    import numpy as np

    if isinstance(value, dict):
        return {k: _poison(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_poison(v) for v in value)
    try:
        arr = np.asarray(value)
    except Exception:
        return value
    if not np.issubdtype(arr.dtype, np.inexact):
        return value
    return np.full_like(arr, np.nan)


class FaultSpec:
    """One armed site: seeded RNG + fire bookkeeping."""

    __slots__ = ("site", "kind", "prob", "seed", "max_fires", "fires", "_rng")

    def __init__(self, site, kind="io", prob=1.0, seed=0, max_fires=None):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {_KINDS})")
        self.site = site
        self.kind = kind
        self.prob = float(prob)
        self.seed = int(seed)
        self.max_fires = None if max_fires is None else int(max_fires)
        self.fires = 0
        self._rng = random.Random(self.seed)

    def should_fire(self):
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        # always draw, even at prob 0/1: the consumed-draw count (and so
        # the fire pattern) then depends only on call count + seed
        hit = self._rng.random() < self.prob
        if hit:
            self.fires += 1
        return hit

    def __repr__(self):
        return (
            f"FaultSpec({self.site}:{self.kind}:{self.prob}:{self.seed}"
            + (f":{self.max_fires}" if self.max_fires is not None else "")
            + f" fires={self.fires})"
        )


_lock = threading.Lock()
_registry: dict[str, FaultSpec] = {}
_env_loaded = False


def parse_spec(text):
    """``site:kind[:prob[:seed[:max_fires]]]`` -> FaultSpec."""
    parts = text.strip().split(":")
    if len(parts) < 2 or not parts[0]:
        raise ValueError(
            f"bad fault spec {text!r}: want site:kind[:prob[:seed[:max_fires]]]"
        )
    site, kind = parts[0], parts[1]
    prob = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
    seed = int(parts[3]) if len(parts) > 3 and parts[3] else 0
    max_fires = int(parts[4]) if len(parts) > 4 and parts[4] else None
    return FaultSpec(site, kind, prob, seed, max_fires)


def inject(site, kind="io", prob=1.0, seed=0, max_fires=None):
    """Arm `site` programmatically; replaces any prior spec for the site
    (including one from the env — and a LATER lazy env load never clobbers
    a programmatic arm, so the env is drained eagerly here first)."""
    _ensure_env_loaded()
    spec = FaultSpec(site, kind, prob, seed, max_fires)
    with _lock:
        _registry[spec.site] = spec
    return spec


def clear(site=None):
    """Disarm one site, or every site (also forgets the env config)."""
    global _env_loaded
    _ensure_env_loaded()  # so a later lazy env load cannot re-arm the site
    with _lock:
        if site is None:
            _registry.clear()
        else:
            _registry.pop(site, None)


def reload_env(value=None):
    """(Re)parse ``PADDLE_TPU_FAULT_INJECT`` (or `value`) into the registry."""
    global _env_loaded
    text = os.environ.get(FAULT_ENV_VAR, "") if value is None else value
    specs_ = []
    for chunk in text.replace(";", ",").split(","):
        if chunk.strip():
            specs_.append(parse_spec(chunk))
    with _lock:
        for spec in specs_:
            _registry[spec.site] = spec
        _env_loaded = True
    return specs_


def _ensure_env_loaded():
    """First-use env load, check-and-apply under ONE lock hold: concurrent
    first callers (e.g. two dataloader workers) must not each re-parse the
    env — the second parse would replace armed specs and reset their fires
    counters, breaking max_fires determinism."""
    global _env_loaded
    with _lock:
        if _env_loaded:
            return
        text = os.environ.get(FAULT_ENV_VAR, "")
        for chunk in text.replace(";", ",").split(","):
            if chunk.strip():
                spec = parse_spec(chunk)
                _registry[spec.site] = spec
        _env_loaded = True


def specs():
    """Snapshot of armed sites (site -> FaultSpec)."""
    with _lock:
        return dict(_registry)


def _draw(site):
    """Shared seam core: None when `site` is free or its draw missed, else
    the armed kind that fired (the fire is counted here)."""
    if not _env_loaded:
        _ensure_env_loaded()
    if not _registry:  # benign unlocked read: the common all-clear fast path
        return None
    with _lock:
        spec = _registry.get(site)
        fire = spec.should_fire() if spec is not None else False
    if not fire:
        return None
    from .. import observability as _obs

    _obs.add("resilience.faults_injected")
    _obs.add(f"resilience.faults_injected.{site}")
    return spec.kind


def fault_point(site):
    """The raise-style seam: no-op unless `site` is armed and its draw
    fires. A fired ``hang``/``slow`` sleeps instead of raising;
    ``nonfinite`` at a raise-only seam degrades to raising
    NonFiniteError."""
    kind = _draw(site)
    if kind is None:
        return
    if kind == "hang":
        time.sleep(_hang_seconds())
        return
    if kind == "slow":
        time.sleep(_slow_seconds())
        return
    raise _make_error(kind, site)


def corrupt_point(site, value):
    """The value-corrupting seam: returns `value` (possibly poisoned).
    A fired ``nonfinite`` NaN-fills every float array inside `value`;
    ``hang`` sleeps then passes `value` through; raising kinds raise as at
    :func:`fault_point`."""
    kind = _draw(site)
    if kind is None:
        return value
    if kind == "hang":
        time.sleep(_hang_seconds())
        return value
    if kind == "slow":
        time.sleep(_slow_seconds())
        return value
    if kind == "nonfinite":
        return _poison(value)
    raise _make_error(kind, site)
