"""Resilience subsystem: retry/backoff, deterministic fault injection,
durable-checkpoint verification, hang-proof pipelines.

The production seams live where the failures live — `io.py` (atomic,
CRC-manifested checkpoints), `fleet/collective.py` (retried publish +
newest-valid fallback), `dataloader/dataloader_iter.py` (retried fetch,
dead-worker resubmission, shutdown-safe get), `distributed/launch.py`
(--elastic child restarts) — and this package provides the two primitives
they share:

* :func:`retry` — exponential backoff with full jitter, per-attempt
  timeout, overall deadline, a retryable-exception classifier, and
  ``resilience.retries`` / ``resilience.giveups`` counters;
* :mod:`faults` — the ``PADDLE_TPU_FAULT_INJECT`` registry whose
  :func:`fault_point` / :func:`corrupt_point` seams make every one of
  those paths chaos-testable deterministically;
* :mod:`health` — per-rank :class:`Heartbeat` liveness files +
  :class:`StepWatchdog` stall monitor, and the preemption exit-code
  contract (:data:`PREEMPTION_EXIT_CODE`) the launcher honors;
* :mod:`guard` — :class:`TrainGuard`, the step-loop wrapper tying it all
  together: always-on fused finite checks with bad-step skip, AMP
  loss-scale feedback, checkpoint rollback after K consecutive bad
  steps, and SIGTERM drain-to-checkpoint;
* :mod:`storage` — the storage fault domain: :class:`StorageMonitor`
  free-space/write-latency probes with a hysteresis-latched pressure
  level, :class:`RetentionManager` cross-plane GC, and the
  :class:`StoragePressureController` degradation ladder (SOFT → HARD →
  CRITICAL) every durable plane degrades along instead of dying on
  ENOSPC.

README §Resilience and §Training health guard document the fault-site
catalog, env syntax, metric names, and the recovery policy knobs.
"""

from __future__ import annotations

from . import faults, guard as _guard_mod, health  # noqa: F401
from . import retry as _retry_mod  # noqa: F401
from .faults import (  # noqa: F401
    FAULT_ENV_VAR,
    FaultSpec,
    clear,
    corrupt_point,
    fault_point,
    inject,
    reload_env,
)
from .guard import TrainGuard  # noqa: F401
from .health import (  # noqa: F401
    PREEMPTION_EXIT_CODE,
    Heartbeat,
    LivenessPulse,
    StepWatchdog,
    heartbeat_path,
    read_beat,
)
from .retry import backoff_delay, default_retryable, retry  # noqa: F401
from .storage import (  # noqa: F401
    RetentionManager,
    StorageMonitor,
    StoragePressureController,
    require_writable,
)
from .supervisor import Supervisor  # noqa: F401
