"""Resilience subsystem: retry/backoff, deterministic fault injection,
durable-checkpoint verification, hang-proof pipelines.

The production seams live where the failures live — `io.py` (atomic,
CRC-manifested checkpoints), `fleet/collective.py` (retried publish +
newest-valid fallback), `dataloader/dataloader_iter.py` (retried fetch,
dead-worker resubmission, shutdown-safe get), `distributed/launch.py`
(--elastic child restarts) — and this package provides the two primitives
they share:

* :func:`retry` — exponential backoff with full jitter, per-attempt
  timeout, overall deadline, a retryable-exception classifier, and
  ``resilience.retries`` / ``resilience.giveups`` counters;
* :mod:`faults` — the ``PADDLE_TPU_FAULT_INJECT`` registry whose
  :func:`fault_point` seams make every one of those paths chaos-testable
  deterministically.

README §Resilience documents the fault-site catalog, env syntax, metric
names, and the checkpoint durability guarantees.
"""

from __future__ import annotations

from . import faults, retry as _retry_mod  # noqa: F401
from .faults import (  # noqa: F401
    FAULT_ENV_VAR,
    FaultSpec,
    clear,
    fault_point,
    inject,
    reload_env,
)
from .retry import backoff_delay, default_retryable, retry  # noqa: F401
