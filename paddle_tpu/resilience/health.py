"""Heartbeat liveness + step watchdog (the health half of the guard layer).

A hung step is the failure mode PR 2 could not see: the elastic launcher
only notices children that *exit*, while a rank stuck in a collective or a
starved input pipeline blocks forever with a perfectly healthy process
table. The fix is a liveness contract:

* each trainer owns a :class:`Heartbeat` and touches it once per step —
  a single small file ``{dir}/hb_rank{K}`` holding a monotonic step
  counter plus a wall-clock timestamp, published atomically with the PR-2
  temp+``os.replace`` idiom so the launcher never reads a torn beat;
* the launcher (``--heartbeat_dir/--heartbeat_timeout``) reads the beats
  from its supervision loop and treats a stale one like a dead child:
  SIGTERM→SIGKILL the hung rank and route it through the ``--elastic``
  restart path (``resilience.hangs`` counters);
* in-process, a :class:`StepWatchdog` monitor thread invokes a callback
  when no beat/touch lands within its timeout — the cheap way for a
  single-process loop to self-report a stall it cannot unblock.

The preemption half of the contract lives here too:
:data:`PREEMPTION_EXIT_CODE` is the distinguished exit code a drained
trainer exits with after a SIGTERM (guard.py); the launcher treats it as a
clean exit — no pod abort, no restart-budget burn.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

__all__ = [
    "HEARTBEAT_DIR_ENV",
    "HEARTBEAT_TIMEOUT_ENV",
    "PREEMPTION_EXIT_CODE",
    "Heartbeat",
    "LivenessPulse",
    "StepWatchdog",
    "heartbeat_path",
    "read_beat",
]

# Exit code a preempted (SIGTERM-drained) trainer exits with after writing
# its final checkpoint. 75 is EX_TEMPFAIL ("temporary failure, retry
# later") — exactly the semantics of a preemption — and collides with no
# Python/pytest/signal convention (negative codes and 128+N mean "killed
# by signal N" to the launcher's Popen).
PREEMPTION_EXIT_CODE = 75

# Env plumbing: the launcher exports these so a TrainGuard/Heartbeat in
# the child auto-configures without flag threading.
HEARTBEAT_DIR_ENV = "PADDLE_HEARTBEAT_DIR"
HEARTBEAT_TIMEOUT_ENV = "PADDLE_HEARTBEAT_TIMEOUT"


def heartbeat_path(directory, rank):
    """The beat file for `rank` — the {dir}/hb_rank{K} naming contract
    shared by Heartbeat (writer) and the launcher (reader)."""
    return os.path.join(directory, f"hb_rank{int(rank)}")


def read_beat(path):
    """Parse one beat file -> dict(rank, step, time), or None when the file
    is missing or torn (a beat mid-publish is indistinguishable from no
    beat; the next poll sees the full one)."""
    try:
        with open(path) as f:
            beat = json.load(f)
    except (OSError, ValueError):
        return None
    return beat if isinstance(beat, dict) else None


class Heartbeat:
    """Per-rank liveness file a trainer touches once per step.

    ``beat()`` bumps a monotonic step counter and atomically publishes
    ``{"rank": K, "step": N, "time": wall}`` to ``{dir}/hb_rank{K}``
    (temp file + ``os.replace`` in the same directory — a reader never
    sees a torn write). Wall-clock time is deliberate: launcher and
    trainer are different processes and the launcher compares the beat
    against its own clock.

    `directory`/`rank` default from the launcher's env
    (PADDLE_HEARTBEAT_DIR / PADDLE_TRAINER_ID), so library code can do
    ``Heartbeat()`` inside any launched trainer.
    """

    def __init__(self, directory=None, rank=None, _time=time.time):
        if directory is None:
            directory = os.environ.get(HEARTBEAT_DIR_ENV)
        if directory is None:
            raise ValueError(
                "Heartbeat needs a directory (arg or "
                f"{HEARTBEAT_DIR_ENV} env)"
            )
        if rank is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.directory = directory
        self.rank = int(rank)
        self.step = 0
        self._time = _time
        # sticky key/value stamps merged into every beat (e.g. the live
        # publish plane's model_version): a fleet reader can tell which
        # model version a worker serves from its beat file alone
        self._stamps = {}
        # beat() is called from the step loop AND (during an async
        # checkpoint publish) from the publisher's liveness pulse; the
        # counter bump + tmp/replace pair must not interleave
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        # a dead predecessor's failed beat publish leaves hb_rankK.tmp.*
        # behind; this rank owns that prefix, sibling ranks own theirs
        from .. import io as _io

        _io.sweep_stale_tmp(directory, prefix=f"hb_rank{self.rank}")

    @property
    def path(self):
        return heartbeat_path(self.directory, self.rank)

    def beat(self, step=None):
        """Publish one liveness beat (and return its payload). `step`
        overrides the monotonic counter (e.g. to resume after a restart
        from a checkpointed step number)."""
        from .faults import fault_point

        # the chaos seam: an armed "hang" sleeps HERE, i.e. the beat never
        # lands — exactly what a stuck collective looks like to a watcher
        fault_point("health.beat")
        with self._lock:
            self.step = self.step + 1 if step is None else int(step)
            payload = self._publish_locked()
        from .. import observability as _obs

        _obs.add("resilience.heartbeats")
        return payload

    def _publish_locked(self):
        """Write the current counter + a fresh timestamp to the beat file
        (lock held by the caller). When a TraceContext is active on the
        beating thread, its ids are stamped into the payload — the
        cross-RANK leg of causal tracing: per-rank span exports plus
        these beat stamps let ``perf_report --merge`` stitch one pod-wide
        causal timeline (a beat names the trace its rank's current step
        belongs to)."""
        payload = {
            "rank": self.rank, "step": self.step, "time": self._time()
        }
        if self._stamps:
            payload.update(self._stamps)
        from ..observability import trace as _trace

        ctx = _trace.current()
        if ctx is not None:
            payload.update(ctx.to_dict())
        # telemetry-journal stamp (PR 16): the shard name plus the latest
        # (seq, byte offset) this process has journaled — a fleet reader
        # comparing two beats can tell "rank alive but journal stale"
        # (offset frozen) from "rank gone" (beat stale), with no access
        # to the rank's memory
        from ..observability import timeline as _timeline

        stamp = _timeline.journal_stamp()
        if stamp is not None:
            payload.update(stamp)
        fd, tmp = tempfile.mkstemp(
            dir=self.directory, prefix=f"hb_rank{self.rank}.tmp."
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return payload

    def set_stamp(self, key, value):
        """Set a sticky stamp merged into every subsequent beat/touch and
        republish immediately (so the stamp lands even on an idle rank).
        Reserved payload keys (rank/step/time) are refused."""
        if key in ("rank", "step", "time"):
            raise ValueError(f"heartbeat stamp key {key!r} is reserved")
        with self._lock:
            self._stamps[str(key)] = value
            return self._publish_locked()

    def touch(self):
        """Republish the CURRENT step with a fresh wall-clock time — an
        "alive, still on the same step" beat. A slow checkpoint publish
        pulses this so the launcher's stale-beat watcher never mistakes a
        long fsync/upload for a hung step. The counter is read and
        republished under the lock, so a concurrent per-step ``beat()``
        can never be regressed by a racing touch (the step counter stays
        monotonic per *training* step, which restart logic relies on).
        Deliberately NOT routed through the ``health.beat`` fault seam:
        a touch is a liveness refresh, not a step beat, and it must not
        consume the seam's seeded draws."""
        with self._lock:
            return self._publish_locked()


class LivenessPulse:
    """Context manager: a daemon thread calling `touch_cb` every
    `interval` seconds while the body runs.

    Wrapped around a checkpoint save — synchronous or on the async
    publisher thread — it keeps heartbeats/watchdog touches landing while
    a single slow stage (one big fsync, one slow ``fs.upload``) blocks;
    per-stage beats alone would starve exactly when they matter most.
    Callback exceptions are swallowed: a broken beat must not fail a
    save."""

    def __init__(self, touch_cb, interval=0.25):
        self._cb = touch_cb
        self._interval = float(interval)
        self._stop = threading.Event()
        self._thread = None
        self._ctx = None

    def __enter__(self):
        if self._cb is not None:
            from ..observability import trace as _trace

            # capture/activate handoff onto the pulse thread: the pulse
            # span files under whatever the guarded body runs in (the
            # async publish span, the sync save's step trace), so a
            # trace of a slow save SHOWS its liveness pulses
            self._ctx = _trace.capture()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="liveness-pulse"
            )
            self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self._interval * 4 + 1.0)
        return False

    def _run(self):
        from .. import observability as _obs
        from ..observability import trace as _trace

        # ONE span for the pulse thread's whole life (per-tick spans
        # would flood the ring buffer on a genuinely slow upload)
        with _trace.activate(self._ctx), \
                _obs.span("health.pulse", category="health"):
            while not self._stop.wait(self._interval):
                try:
                    self._cb()
                except Exception:
                    pass


class StepWatchdog:
    """Monitor thread that fires when no ``touch()`` lands within `timeout`.

    The thread cannot raise into the training thread (Python offers no
    safe cross-thread raise), so stalls are delivered through `on_stall`:
    ``on_stall(stalled_seconds)`` — default logs to stderr. Every stall
    bumps ``resilience.hangs`` (plus ``resilience.hangs.<name>``); the
    watchdog fires ONCE per stall and re-arms on the next touch, so a
    30-minute hang is one event, not one per poll.

    Usable as a context manager::

        with StepWatchdog(timeout=60, on_stall=dump_stacks) as wd:
            for batch in loader:
                train_step(batch)
                wd.touch()
    """

    def __init__(self, timeout, on_stall=None, name=None,
                 poll_interval=None, clock=time.monotonic):
        if timeout <= 0:
            raise ValueError("StepWatchdog timeout must be > 0")
        self.timeout = float(timeout)
        self.on_stall = on_stall
        self.name = name
        self.stalls = 0
        self._poll = (
            float(poll_interval) if poll_interval is not None
            else max(0.01, min(self.timeout / 4.0, 1.0))
        )
        self._clock = clock
        self._last = clock()
        self._fired = False
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    def touch(self):
        """Record liveness; also re-arms the watchdog after a stall."""
        with self._lock:
            self._last = self._clock()
            self._fired = False

    def start(self):
        if self._thread is not None:
            return self
        self.touch()  # the clock starts at start(), not __init__
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"step-watchdog-{self.name or 'anon'}",
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self._poll * 4 + 1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self._poll):
            with self._lock:
                stalled = self._clock() - self._last
                fire = stalled > self.timeout and not self._fired
                if fire:
                    self._fired = True
            if not fire:
                continue
            self.stalls += 1
            from .. import observability as _obs
            from ..observability import recorder as _recorder

            _obs.add("resilience.hangs")
            if self.name:
                _obs.add(f"resilience.hangs.{self.name}")
            # flight-recorder trigger: a hang the launcher is about to
            # kill -9 for is exactly the death whose last window would
            # otherwise be unrecoverable — dump it while still alive
            _recorder.flight_dump("watchdog_stall", detail={
                "stalled_s": stalled, "timeout_s": self.timeout,
                "name": self.name,
            })
            if self.on_stall is not None:
                try:
                    self.on_stall(stalled)
                except Exception:
                    pass  # a broken callback must not kill the monitor
            else:
                import sys

                print(
                    f"[StepWatchdog{f' {self.name}' if self.name else ''}] "
                    f"no step in {stalled:.1f}s (timeout {self.timeout}s)",
                    file=sys.stderr,
                )
