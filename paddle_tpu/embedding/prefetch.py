"""Async prefetch: stage the next batch's cold rows while this step runs.

The reference overlapped pserver pulls with compute via the communicator's
send/recv threads (communicator.cc); here the host-side half of a cached
lookup — id extraction, batch-unique, cold-row gather from the host store —
runs on a worker thread one (or more) batches ahead, so by the time the
step loop asks for batch k+1 its rows are already in a staged payload and
the only on-thread work is the slot install + id translation.

Works over any iterable of feed dicts, including a ``DataLoader`` (the
"pipelined via the dataloader" composition: DataLoader workers parse, the
prefetcher stages embedding rows, the executor computes — three
overlapping stages).

Telemetry: ``embedding.prefetch_overlap`` histogram (fraction of each
batch's staging time hidden behind compute: 1.0 = fully overlapped),
``embedding.prefetch_batches`` counter.
"""

from __future__ import annotations

import queue
import threading
import time

from .cache import RATIO_BUCKETS


class Prefetcher:
    """Iterate ``feeds``, returning feeds whose cached-table ids are
    already resident and translated to hot slots.

    depth: staged batches the worker may run ahead (>= 1). The worker only
    does plan() (host reads, thread-safe vs the residency lock); apply()
    (device slot writes + translation) happens on the consuming thread at
    ``__next__`` so it is serialized with the step loop.
    """

    def __init__(self, engine, feeds, scope, depth=2):
        from ..observability import trace

        if depth < 1:
            raise ValueError(f"Prefetcher depth must be >= 1, got {depth}")
        self.engine = engine
        self.scope = scope
        self._q = queue.Queue(maxsize=int(depth))
        self._src = iter(feeds)
        self._done = object()
        self._err = None
        self._stop = threading.Event()
        # capture/activate handoff: plan spans on the worker thread file
        # under the trace that CONSTRUCTED the prefetcher (a restarted
        # prefetcher re-captures, so the restart joins the live trace)
        self._ctx = trace.capture()
        self._thread = threading.Thread(
            target=self._worker, name="embedding-prefetch", daemon=True
        )
        self._thread.start()

    def _put(self, item):
        """put() that yields to the stop flag so close() cannot leave the
        worker blocked on a full queue (and then silently iterating the
        rest of the feed source)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        from .. import observability as _obs
        from ..observability import trace

        try:
            with trace.activate(self._ctx):
                for feed in self._src:
                    if self._stop.is_set():
                        break
                    t0 = time.perf_counter()
                    with _obs.span("embedding.prefetch_plan",
                                   category="embedding"):
                        plans = self.engine.plan(feed)
                    prep = time.perf_counter() - t0
                    if not self._put((feed, plans, prep)):
                        break
            self._put(self._done)
        except BaseException as e:  # surfaced on the consumer thread
            self._err = e
            self._put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        from .. import observability as _obs

        t_req = time.perf_counter()
        item = self._q.get()
        if item is self._done:
            # keep the sentinel visible: a second next() (or close())
            # after exhaustion/error must not block forever
            self._stop.set()
            try:
                self._q.put_nowait(self._done)
            except queue.Full:
                pass
            self._thread.join(timeout=5)
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        feed, plans, prep = item
        waited = max(0.0, time.perf_counter() - t_req)
        if prep > 0:
            # the slice of staging time the consumer actually waited for is
            # the non-overlapped part; everything else ran behind compute
            overlap = max(0.0, 1.0 - min(waited, prep) / prep)
            _obs.observe("embedding.prefetch_overlap", overlap,
                         RATIO_BUCKETS)
        _obs.add("embedding.prefetch_batches")
        return self.engine.apply(plans, feed, self.scope)

    def close(self):
        """Stop the worker and drain the queue (for early exit from the
        consuming loop): the stop flag halts both the feed iteration and
        any put() in flight, so no further feeds are consumed."""
        self._stop.set()
        while self._thread.is_alive():
            try:
                self._q.get(timeout=0.1)
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        # leave nothing stranded for a consumer still holding the iterator
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
