"""TPU-native embedding engine (PR 11) — the recommendation workhorse.

The reference's large-scale sparse stack (PAPER.md L3/L8: FleetWrapper /
pslib, distributed lookup_table, BoxPS caches) re-architected for the XLA
compilation model, in four pieces:

* :func:`fuse_lookups` (engine.py) — same-width ``sparse_embedding``
  lookups coalesce into ONE ``fused_lookup_table`` op over a concatenated
  id space: batch-unique ids dedup once, one gather serves every slot,
  backward is one segment-sum scatter per table (DeepFM: 26+1 gather
  dispatches -> 2).
* sharded tables (parallel/sparse.py) — row- or column-partition over a
  mesh axis, with an opt-in PR-9 int8 block-quant wire for the embedding
  gradient exchange (``parallel.quantize_embedding_grads``).
* :class:`CachedTable` tiers (cache.py) — a frequency-tracked hot-rows
  tier resident on device with a host-memory cold path, so ``vocab_size``
  can exceed one device's HBM; eviction by access count, write-back of
  trained rows + optimizer state.
* :class:`Prefetcher` (prefetch.py) — the next batch's ids are extracted
  and their cold rows staged host-side while the current step computes.

Telemetry lands under ``embedding.*`` (hit-rate gauges, host-fetch /
prefetch-overlap / unique-ids histograms); README §Embedding engine has
the knobs and the capacity math.
"""

from .cache import CachedGroup  # noqa: F401
from .engine import EmbeddingEngine, fuse_lookups  # noqa: F401
from .prefetch import Prefetcher  # noqa: F401
