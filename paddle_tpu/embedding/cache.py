"""Host-cold / device-hot cache tiers for sparse tables.

The capability target is the reference's pslib/BoxPS cache hierarchy
(fleet_wrapper.h:86: pull_sparse into a device cache, push back on
eviction) re-architected for the XLA model: the traced program only ever
gathers from a fixed-shape device-resident hot tier ([hot_rows, D] — a
plain persistable parameter), and every id the host feeds is pre-translated
to a hot slot. The cold store is host memory (numpy), so table capacity is
bounded by host RAM, not device HBM.

A :class:`CachedGroup` is one id space shared by one or more tables (e.g.
DeepFM's first-order [V, 1] and factor [V, D] tables both keyed by
``feat_ids``): one slot map + one access-count array serve every table in
the group, so a single host-side translation covers all of them and their
rows stay slot-aligned across tiers.

Eviction is by access count (coldest resident row first), never evicting a
row the incoming batch needs; evicted rows (and their optimizer-state rows)
write back to the host store so training state survives the round trip.
Telemetry: ``embedding.cache_{hits,misses,evictions,writebacks}`` counters,
``embedding.hot_hit_rate.<group>`` gauge, ``embedding.host_fetch_latency``
histogram.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..errors import InvalidArgumentError, PreconditionNotMetError

#: fraction buckets for ratio-valued histograms (hit rates, overlap)
RATIO_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                 0.95, 1.0)
#: count buckets for per-batch id histograms
COUNT_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                 16384, 65536)


class _Plan:
    """Host-side prep for one batch of one group (prefetch-safe): the
    unique global ids, the ids missing at plan time, and their host row
    payloads per table."""

    __slots__ = ("group", "unique", "counts", "miss_ids", "payload",
                 "prep_seconds", "tick")

    def __init__(self, group, unique, counts, miss_ids, payload,
                 prep_seconds, tick):
        self.group = group
        self.unique = unique
        self.counts = counts
        self.miss_ids = miss_ids
        self.payload = payload  # {var_name: rows [len(miss_ids), ...]}
        self.prep_seconds = prep_seconds
        self.tick = tick  # write-back clock at plan time (staleness check)


class CachedGroup:
    def __init__(self, table_names, vocab, hot_rows, feeds):
        if hot_rows <= 0 or hot_rows > vocab:
            raise InvalidArgumentError(
                f"CachedGroup({table_names}): hot_rows must be in "
                f"(0, vocab={vocab}], got {hot_rows}"
            )
        self.table_names = list(table_names)
        self.name = self.table_names[0]
        self.vocab = int(vocab)
        self.hot_rows = int(hot_rows)
        self.feeds = list(feeds)
        self.host = {}  # var name -> np [vocab, ...] cold store
        self.accums = {}  # table -> [(accum var name, fill value), ...]
        # residency: global row -> slot (-1 = cold), slot -> global row
        self.slot_of = np.full(self.vocab, -1, np.int64)
        self.row_of = np.full(self.hot_rows, -1, np.int64)
        self.counts = np.zeros(self.vocab, np.int64)
        # per-row write-back clock: a prefetched payload row is stale when
        # the row was written back AFTER the plan snapshotted it (install ->
        # train -> evict all inside the prefetch window)
        self._tick = 0
        self._wb_tick = np.zeros(self.vocab, np.int64)
        # per-consumer delta cursors: the checkpointer and the model
        # publisher each track their own committed tick, so one
        # consumer's publish can never swallow rows from the other's
        # next delta (the shared-mark bug)
        self._cursors = {}
        from collections import deque

        self._free = deque(range(self.hot_rows))
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    # -- wiring ------------------------------------------------------------
    def attach(self, scope, main, accums, init_specs=None):
        """Seed the host cold stores: table values from a deterministic
        host-side replay of the table's DECLARED initializer — the full-
        shape startup-op spec the engine captured before shrinking it
        (the device startup only initialized the hot tier, whose values
        are never-read placeholders) — accumulators from their startup
        fill value."""
        blk = main.global_block
        self.accums = dict(accums)
        for t in self.table_names:
            v = blk.var(t)
            tail = tuple(v.shape[1:])
            dtype = v.dtype or "float32"
            seed = (zlib_crc(t) ^ (main.random_seed or 0)) & 0x7FFFFFFF
            self.host[t] = _replay_init(
                (init_specs or {}).get(t), (self.vocab,) + tail, dtype,
                seed, t,
            )
            for aname, fill in self.accums.get(t, ()):
                av = blk.var(aname)
                self.host[aname] = np.full(
                    (self.vocab,) + tuple(av.shape[1:]), fill,
                    av.dtype or "float32",
                )
        self.reset_residency()

    def reset_residency(self):
        from collections import deque

        with self._lock:
            self.slot_of[:] = -1
            self.row_of[:] = -1
            self._free = deque(range(self.hot_rows))

    def restore_residency(self, row_of, scope):
        """Re-pin a checkpointed slot map (engine.load_state_dict) and
        re-install every resident row's tiers from the host store — the
        host is authoritative after a flush, so this is bitwise-correct
        whether or not the device arrays were also restored."""
        from collections import deque

        with self._lock:
            self.row_of[:] = row_of
            self.slot_of[:] = -1
            slots = np.nonzero(self.row_of >= 0)[0]
            rows = self.row_of[slots]
            self.slot_of[rows] = slots
            self._free = deque(
                int(s) for s in np.nonzero(self.row_of < 0)[0]
            )
            if slots.size:
                for vname in self.host:
                    self._install(
                        scope, vname, slots, self.host[vname][rows]
                    )

    def host_bytes(self):
        return int(sum(a.nbytes for a in self.host.values()))

    def device_bytes(self):
        total = 0
        for t in self.table_names:
            row = self.host[t][0]
            total += self.hot_rows * row.nbytes
            for aname, _f in self.accums.get(t, ()):
                total += self.hot_rows * self.host[aname][0].nbytes
        return int(total)

    # -- per-batch ---------------------------------------------------------
    def plan(self, ids):
        """Host-side prep (thread-safe vs apply): unique the batch's ids,
        snapshot the current miss set and gather its host rows. Rows in
        the payload are non-resident at plan time and the host store only
        changes for RESIDENT rows (write-back), so the payload stays fresh
        until :meth:`apply` re-checks residency."""
        from .. import observability as _obs

        t0 = time.perf_counter()
        flat = np.asarray(ids).reshape(-1)
        if flat.size and (flat.min() < 0 or flat.max() >= self.vocab):
            raise InvalidArgumentError(
                f"CachedGroup {self.name!r}: batch ids outside "
                f"[0, {self.vocab}) (got min {flat.min()}, max "
                f"{flat.max()})"
            )
        unique, occ = np.unique(flat, return_counts=True)
        if unique.size > self.hot_rows:
            raise PreconditionNotMetError(
                f"CachedGroup {self.name!r}: batch has {unique.size} "
                f"unique ids but the hot tier holds {self.hot_rows} rows; "
                "raise hot_rows above the max unique ids per batch"
            )
        with self._lock:
            miss = unique[self.slot_of[unique] < 0]
            tick = self._tick
        payload = {}
        for t in self.table_names:
            payload[t] = self.host[t][miss]
            for aname, _f in self.accums.get(t, ()):
                payload[aname] = self.host[aname][miss]
        prep = time.perf_counter() - t0
        _obs.observe("embedding.unique_ids_per_batch", unique.size,
                     COUNT_BUCKETS)
        if flat.size:
            _obs.observe("embedding.dedup_ratio", unique.size / flat.size,
                         RATIO_BUCKETS)
        return _Plan(self, unique, occ, miss, payload, prep, tick)

    def apply(self, plan, scope):
        """Make every id of the plan resident (write-back + install under
        the residency lock, on the step thread), then bump access counts.
        Misses that appeared since plan time (rows another batch evicted)
        fetch synchronously; rows that BECAME resident skip their stale
        payload."""
        from .. import observability as _obs

        t0 = time.perf_counter()
        with self._lock:
            still_miss = plan.unique[self.slot_of[plan.unique] < 0]
            hits = plan.unique.size - still_miss.size
            self._hits += hits
            self._misses += still_miss.size
            if still_miss.size:
                slots = self._take_slots(still_miss, plan.unique, scope)
                # both arrays are sorted-unique (np.unique output and a
                # mask of it): searchsorted maps each still-missing row to
                # its payload position, no per-element Python on the step
                # thread
                if plan.miss_ids.size:
                    pick = np.clip(
                        np.searchsorted(plan.miss_ids, still_miss),
                        0, plan.miss_ids.size - 1,
                    )
                    planned = plan.miss_ids[pick] == still_miss
                else:
                    pick = np.zeros(still_miss.shape, np.int64)
                    planned = np.zeros(still_miss.shape, bool)
                # a row written back since the plan snapshot carries newer
                # trained state than the prefetched payload — refetch it
                planned &= self._wb_tick[still_miss] <= plan.tick
                for vname in plan.payload:
                    if planned.all():
                        rows = plan.payload[vname][pick]
                    else:
                        # late misses (rows another batch evicted since
                        # plan time): their payload rows are stale or
                        # absent — refetch from the host store
                        rows = self.host[vname][still_miss].copy()
                        if planned.any():
                            rows[planned] = plan.payload[vname][
                                pick[planned]
                            ]
                    self._install(scope, vname, slots, rows)
                self.slot_of[still_miss] = slots
                self.row_of[slots] = still_miss
            self.counts[plan.unique] += plan.counts
        _obs.add("embedding.cache_hits", int(hits))
        _obs.add("embedding.cache_misses", int(still_miss.size))
        if still_miss.size:
            _obs.observe(
                "embedding.host_fetch_latency", time.perf_counter() - t0
            )
        total = self._hits + self._misses
        if total:
            _obs.set_gauge(
                f"embedding.hot_hit_rate.{self.name}", self._hits / total
            )

    def translate(self, ids):
        """Global ids -> hot slot ids (same shape/dtype). Every id must be
        resident (apply() ran for this batch)."""
        arr = np.asarray(ids)
        slots = self.slot_of[arr.reshape(-1)]
        if slots.size and slots.min() < 0:
            raise PreconditionNotMetError(
                f"CachedGroup {self.name!r}: translate() saw a non-resident "
                "id; call apply()/prepare_feed() for this exact batch first"
            )
        return slots.reshape(arr.shape).astype(arr.dtype)

    # -- internals (residency lock held) -----------------------------------
    def _take_slots(self, need, protect, scope):
        """Free or evict len(need) slots; never evicts a row in `protect`
        (the incoming batch). Eviction order: lowest access count."""
        from .. import observability as _obs

        n = need.size
        slots = []
        while self._free and len(slots) < n:
            slots.append(self._free.popleft())
        short = n - len(slots)
        if short > 0:
            resident = self.row_of[self.row_of >= 0]
            evictable = resident[~np.isin(resident, protect,
                                          assume_unique=True)]
            if evictable.size < short:
                raise PreconditionNotMetError(
                    f"CachedGroup {self.name!r}: cannot free {short} slots "
                    f"({evictable.size} evictable rows); raise hot_rows"
                )
            victims = evictable[
                np.argsort(self.counts[evictable], kind="stable")[:short]
            ]
            vslots = self.slot_of[victims]
            self._writeback(scope, victims, vslots)
            self.slot_of[victims] = -1
            self.row_of[vslots] = -1
            slots.extend(int(s) for s in vslots)
            _obs.add("embedding.cache_evictions", int(short))
        return np.asarray(slots[:n], np.int64)

    def _writeback(self, scope, rows, slots):
        """Pull trained slot rows (+ optimizer state) device->host."""
        from .. import observability as _obs

        self._tick += 1
        self._wb_tick[rows] = self._tick
        for t in self.table_names:
            names = [t] + [a for a, _f in self.accums.get(t, ())]
            for vname in names:
                arr = scope.find_var(vname)
                if arr is None:
                    continue
                self.host[vname][rows] = np.asarray(arr[slots])
        _obs.add("embedding.cache_writebacks", int(rows.size))

    def _install(self, scope, vname, slots, rows):
        arr = scope.find_var(vname)
        if arr is None:
            raise PreconditionNotMetError(
                f"cached var {vname!r} is not initialized in the scope; "
                "run the startup program before engine.attach"
            )
        import jax.numpy as jnp

        arr = jnp.asarray(arr).at[jnp.asarray(slots)].set(
            jnp.asarray(rows, dtype=arr.dtype)
        )
        scope.set_var(vname, arr)

    def flush(self, scope):
        with self._lock:
            resident_slots = np.nonzero(self.row_of >= 0)[0]
            if not resident_slots.size:
                return
            rows = self.row_of[resident_slots]
            self._writeback(scope, rows, resident_slots)

    # -- tiered-checkpoint delta hooks ---------------------------------------
    def delta_tick(self):
        """Current write-back clock value — the mark a delta checkpoint
        records so the next save can name exactly the host rows that
        changed since (host stores mutate ONLY through write-back, so
        rows at or below a recorded tick are bitwise unchanged)."""
        with self._lock:
            return int(self._tick)

    def dirty_rows_since(self, tick):
        """Global row indices written back after `tick` — the row-level
        delta payload for every host store of this group."""
        with self._lock:
            return np.nonzero(self._wb_tick > int(tick))[0]

    def consumer_mark(self, consumer):
        """The tick `consumer` (e.g. "checkpoint", "publish") last
        committed, or None before its first full payload."""
        with self._lock:
            return self._cursors.get(consumer)

    def commit_consumer_mark(self, consumer, mark):
        """Advance `consumer`'s committed cursor — call ONLY after the
        payload covering rows up to `mark` durably landed; marks never
        regress, so a stale late commit cannot re-expose rows."""
        with self._lock:
            cur = self._cursors.get(consumer)
            if cur is None or int(mark) > cur:
                self._cursors[consumer] = int(mark)


def zlib_crc(s: str) -> int:
    import zlib

    return zlib.crc32(s.encode())


def _replay_init(spec, shape, dtype, seed, name):
    """Host-side replay of a table's startup init op at the FULL vocab
    shape. The distribution honors the user's declared initializer (the
    attrs carry the concrete bounds Xavier/Uniform/Normal computed at
    build time from the full shape); the draw itself is a deterministic
    numpy stream — device and host PRNGs can never agree bitwise, and the
    cold store is the authoritative init for a cached table."""
    rng = np.random.RandomState(seed)
    op_type, attrs = spec if spec else (None, {})
    if op_type == "fill_constant":
        return np.full(shape, float(attrs.get("value", 0.0)), dtype)
    if op_type == "uniform_random":
        return rng.uniform(
            float(attrs.get("min", -1.0)), float(attrs.get("max", 1.0)),
            shape,
        ).astype(dtype)
    if op_type in ("gaussian_random", "truncated_gaussian_random"):
        std = float(attrs.get("std", 1.0))
        out = rng.normal(float(attrs.get("mean", 0.0)), std, shape)
        if op_type == "truncated_gaussian_random":
            mean = float(attrs.get("mean", 0.0))
            out = np.clip(out, mean - 2 * std, mean + 2 * std)
        return out.astype(dtype)
    import warnings

    warnings.warn(
        f"CachedGroup: no host replay for init op {op_type!r} of table "
        f"{name!r}; falling back to Xavier-uniform over the full shape",
        stacklevel=2,
    )
    fan = shape[0] + (shape[1] if len(shape) > 1 else 1)
    limit = np.sqrt(6.0 / fan)
    return rng.uniform(-limit, limit, shape).astype(dtype)
