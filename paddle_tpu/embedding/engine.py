"""Embedding engine: the fused-lookup transform + the cache orchestrator.

``fuse_lookups`` is a pure Program transform (run it on the forward graph
BEFORE ``optimizer.minimize`` so the fused op is what append_backward
differentiates). ``EmbeddingEngine`` owns the host-cold/device-hot cache
tiers (cache.py) and the feed translation that makes them invisible to the
traced program: the device only ever sees hot-slot ids.
"""

from __future__ import annotations

import numpy as np

from ..errors import InvalidArgumentError
from ..parallel.sparse import LOOKUP_OPS

# ops a lookup's id input may be derived through when walking back to the
# feed that produced it (slice a [B, F] feature block per slot, reshape,
# cast — the host-side translation then rewrites that FEED once)
_ID_CHAIN_OPS = frozenset({
    "slice", "strided_slice", "reshape", "reshape2", "squeeze", "squeeze2",
    "unsqueeze", "unsqueeze2", "cast", "assign", "split", "concat", "stack",
})


def fuse_lookups(program, min_group=2):
    """Coalesce same-width ``distributed_lookup_table`` ops in the global
    block into ``fused_lookup_table`` ops.

    Grouping key: (embed dim, table dtype, axis_name, partition, dedup,
    quant) — every member of a group gathers from the same concatenated
    key space in ONE op; the original output var names are preserved so
    downstream consumers (and the backward pass appended later) are
    untouched. The fused op lands at the LAST member's position (every
    member's ids are produced before it by construction), so interleaved
    slice/lookup chains fuse too; a group closes early when an op between
    members reads one of its outputs (that consumer would otherwise see
    its input produced later).

    Run BEFORE ``optimizer.minimize``: append_backward differentiates the
    fused op into one segment-sum scatter per table. Returns the number of
    fused sites created.
    """
    blk = program.global_block

    groups = []  # [[op_index, ...], ...] in program order
    open_groups = {}  # key -> (groups index, set of member output names)
    for i, op in enumerate(blk.ops):
        if op.type != "distributed_lookup_table":
            # an intermediate reader of a group output pins that group:
            # its members can no longer move past this op
            reads = set(op.input_names())
            for key, (gi, outs) in list(open_groups.items()):
                if reads & outs:
                    del open_groups[key]
            continue
        w = (op.inputs.get("W") or [""])[0]
        ids = (op.inputs.get("Ids") or [""])[0]
        out = (op.outputs.get("Out") or [""])[0]
        if not w or not ids or not out:
            continue
        wv = blk._find_var_recursive(w)
        if wv is None or not wv.shape or len(wv.shape) != 2:
            continue
        key = (
            int(wv.shape[1]), wv.dtype, op.attr("axis_name", "ps"),
            op.attr("partition", "row"), bool(op.attr("dedup", True)),
            op.attr("quant", "none") or "none",
            int(op.attr("quant_block", 256) or 256),
        )
        if key in open_groups:
            gi, outs = open_groups[key]
            groups[gi].append(i)
            outs.add(out)
        else:
            groups.append([i])
            open_groups[key] = (len(groups) - 1, {out})

    fused = 0
    drop = set()
    for members in groups:
        if len(members) < max(int(min_group), 2):
            continue
        ops = [blk.ops[i] for i in members]
        first = ops[0]
        # slots sharing one table (DeepFM per_slot: every slot reads the
        # same shared table) must share ONE key-space segment, or the same
        # id in two slots would get two distinct keys (no cross-slot
        # dedup) and the gather operand would concatenate F aliases of
        # one table: the W slot carries each table ONCE, and
        # slot_table_idx maps every ids slot to its table segment
        uniq, slot_idx = [], []
        for o in ops:
            w = o.inputs["W"][0]
            if w not in uniq:
                uniq.append(w)
            slot_idx.append(uniq.index(w))
        blk.ops[members[-1]] = type(first)(
            blk, "fused_lookup_table",
            inputs={
                "Ids": [o.inputs["Ids"][0] for o in ops],
                "W": uniq,
            },
            outputs={"Out": [o.outputs["Out"][0] for o in ops]},
            attrs={
                "axis_name": first.attr("axis_name", "ps"),
                "partition": first.attr("partition", "row"),
                "dedup": bool(first.attr("dedup", True)),
                "quant": first.attr("quant", "none") or "none",
                "quant_block": int(first.attr("quant_block", 256) or 256),
                "slot_table_idx": slot_idx,
                "__loc__": first.attr("__loc__", ""),
            },
        )
        drop.update(members[:-1])
        fused += 1
    if drop:
        blk.ops = [op for i, op in enumerate(blk.ops) if i not in drop]
    program._bump()
    from .. import observability as _obs

    if fused:
        _obs.add("embedding.fuse_transforms", fused)
    return fused


def _feed_sources(program, name, depth=0):
    """Walk a lookup id input back through slice/reshape-style producers to
    the data (feed) vars it derives from."""
    blk = program.global_block
    v = blk._find_var_recursive(name)
    if v is not None and v.is_data:
        return {name}
    if depth > 8:
        return set()
    out = set()
    for op in blk.ops:
        if name not in op.output_names():
            continue
        if op.type not in _ID_CHAIN_OPS:
            return set()  # unsupported derivation (e.g. computed ids)
        for n in op.input_names():
            if n:
                out |= _feed_sources(program, n, depth + 1)
        break
    return out


class EmbeddingEngine:
    """Host-cold / device-hot tiering for sparse tables.

    Usage (order matters — the hot tier must exist before minimize so the
    optimizer's accumulators are hot-sized too)::

        loss, pred = deepfm(ids, label, cfg, per_slot=True)
        fuse_lookups(main)
        engine = EmbeddingEngine(main, startup,
                                 hot_rows={"deepfm_emb": 4096,
                                           "deepfm_w1": 4096})
        optimizer.SGD(lr).minimize(loss)
        engine.attach(scope)          # after exe.run(startup)
        for feed in Prefetcher(engine, feeds, scope):
            exe.run(main, feed=feed, ...)

    Tables sharing an id feed (DeepFM's first-order + factor tables both
    read ``feat_ids``) form one :class:`~paddle_tpu.embedding.cache.CachedGroup`
    with a shared slot map, so one translation serves both. ``hot_rows``
    may be an int (every table) or {table: rows}. Parity contract: with a
    stateless update rule (SGD) the cached run is bitwise-identical to the
    full-table run; stateful rules (Adam) get the reference's lazy sparse
    semantics (absent rows' moments do not decay).
    """

    def __init__(self, main, startup, hot_rows, tables=None):
        from ..parallel.sparse import sparse_table_names

        self.main = main
        self.startup = startup
        all_tables = sparse_table_names(main)
        if tables is None:
            tables = (
                sorted(hot_rows) if isinstance(hot_rows, dict) else all_tables
            )
        unknown = [t for t in tables if t not in all_tables]
        if unknown:
            raise InvalidArgumentError(
                f"EmbeddingEngine: {unknown} are not sparse tables of this "
                f"program (tables: {all_tables})"
            )
        self._hot = {
            t: int(hot_rows[t] if isinstance(hot_rows, dict) else hot_rows)
            for t in tables
        }
        self.groups = []
        self._feed_to_group = {}
        self._build_groups()
        self._convert()
        self._attached = False

    # -- program rewrite ---------------------------------------------------
    def _build_groups(self):
        from .cache import CachedGroup

        feed_sets = {}  # table -> frozenset of feeds
        for blk in self.main.blocks:
            for op in blk.ops:
                if op.type not in LOOKUP_OPS:
                    continue
                ids_list = op.inputs.get("Ids", ())
                w_list = op.inputs.get("W", ())
                # slot -> table via the fused op's slot_table_idx (the W
                # slot carries each table once; a plain zip would pair
                # only the first len(W) id slots and silently drop the
                # rest of the feeds from the group)
                slot_idx = op.attr("slot_table_idx")
                if slot_idx is None:
                    slot_idx = (
                        [0] * len(ids_list) if len(w_list) == 1
                        else list(range(len(ids_list)))
                    )
                for i, ids in enumerate(ids_list):
                    w = w_list[slot_idx[i]]
                    if w not in self._hot:
                        continue
                    srcs = _feed_sources(self.main, ids)
                    if not srcs:
                        raise InvalidArgumentError(
                            f"EmbeddingEngine: cannot trace the ids of "
                            f"cached table {w!r} back to a feed variable "
                            f"(id input {ids!r} is computed in-graph); the "
                            "host-side id translation needs feed-level ids"
                        )
                    feed_sets.setdefault(w, set()).update(srcs)
        missing = [t for t in self._hot if t not in feed_sets]
        if missing:
            raise InvalidArgumentError(
                f"EmbeddingEngine: no lookup op consumes tables {missing}"
            )
        by_feeds = {}
        for t, feeds in feed_sets.items():
            by_feeds.setdefault(frozenset(feeds), []).append(t)
        blk = self.main.global_block
        for feeds, tabs in sorted(by_feeds.items(), key=lambda kv: kv[1]):
            vocabs = {int(blk.var(t).shape[0]) for t in tabs}
            if len(vocabs) != 1:
                raise InvalidArgumentError(
                    f"EmbeddingEngine: tables {sorted(tabs)} share id feed "
                    f"{sorted(feeds)} but have different (padded) vocabs "
                    f"{sorted(vocabs)}; they cannot share one slot map"
                )
            hots = {self._hot[t] for t in tabs}
            if len(hots) != 1:
                raise InvalidArgumentError(
                    f"EmbeddingEngine: tables {sorted(tabs)} share one slot "
                    f"map and must share hot_rows, got {sorted(hots)}"
                )
            group = CachedGroup(
                sorted(tabs), vocab=vocabs.pop(), hot_rows=hots.pop(),
                feeds=sorted(feeds),
            )
            self.groups.append(group)
            for f in feeds:
                if f in self._feed_to_group:
                    raise InvalidArgumentError(
                        f"EmbeddingEngine: feed {f!r} feeds cached tables "
                        "in two different groups; merge their vocab spaces"
                    )
                self._feed_to_group[f] = group

    def _convert(self):
        """Shrink every cached table (and later its accumulators/grads,
        which minimize will create at the already-shrunk shape) to the
        hot-tier row count, in main + startup, including the startup init
        op — the full [V, D] tensor never materializes on device. The
        init op's ORIGINAL full-shape spec is captured first: it is the
        table's real initialization, replayed host-side into the cold
        store (the shrunk device init is a never-read placeholder)."""
        self._init_specs = {}
        for g in self.groups:
            for t in g.table_names:
                for prog in (self.main, self.startup):
                    v = prog.global_block.vars.get(t)
                    if v is None:
                        continue
                    if v.shape[0] != g.vocab:
                        raise InvalidArgumentError(
                            f"EmbeddingEngine: table {t!r} already has "
                            f"{v.shape[0]} rows (expected {g.vocab}); "
                            "construct the engine before minimize and "
                            "only once"
                        )
                    v.shape = (g.hot_rows,) + tuple(v.shape[1:])
                for op in self.startup.global_block.ops:
                    if t in op.output_names() and "shape" in op.attrs:
                        self._init_specs[t] = (op.type, dict(op.attrs))
                        shape = list(op.attrs["shape"])
                        shape[0] = g.hot_rows
                        op.attrs["shape"] = shape
        self.main._bump()
        self.startup._bump()

    # -- runtime -----------------------------------------------------------
    def attach(self, scope):
        """Bind the engine to a scope AFTER ``exe.run(startup)``: discover
        the (hot-sized) optimizer accumulators, seed the host cold stores,
        and mark every hot slot empty (the startup-initialized hot values
        are placeholders; first-touch misses install the real rows)."""
        blk = self.main.global_block
        for g in self.groups:
            accums = {}
            for name, v in blk.vars.items():
                parent = getattr(v, "_accum_of", None)
                if (
                    parent in g.table_names
                    and v.shape
                    and v.shape[0] == g.hot_rows
                ):
                    accums.setdefault(parent, []).append(
                        (name, self._startup_fill(name))
                    )
            g.attach(scope, self.main, accums,
                     init_specs=self._init_specs)
        self._attached = True
        from .. import observability as _obs

        for g in self.groups:
            _obs.set_gauge(f"embedding.hot_rows.{g.name}", g.hot_rows)
            _obs.set_gauge(f"embedding.vocab_rows.{g.name}", g.vocab)
            _obs.set_gauge(f"embedding.host_bytes.{g.name}", g.host_bytes())
            _obs.set_gauge(
                f"embedding.device_bytes.{g.name}", g.device_bytes()
            )

    def _startup_fill(self, name):
        """Constant fill value of an accumulator's startup init (its host
        mirror must cold-start absent rows at the same value)."""
        for op in self.startup.global_block.ops:
            if name in op.output_names():
                return float(op.attr("value", 0.0) or 0.0)
        return 0.0

    def plan(self, feed):
        """Host-side prep for one batch (safe off-thread): ONE plan per
        group, covering every id feed of the group present in this batch
        (a multi-feed group must see its ids together — per-feed plans
        would translate the same feed twice in apply). Returns an opaque
        plan list for :meth:`apply`."""
        self._check_attached()
        plans = []
        for g in self.groups:
            present = [f for f in g.feeds if f in feed]
            if not present:
                continue
            ids = np.concatenate(
                [np.asarray(feed[f]).reshape(-1) for f in present]
            )
            plans.append(g.plan(ids))
        return plans

    def apply(self, plans, feed, scope):
        """Install a plan's rows (miss fetch + eviction write-back), then
        translate the id feeds to hot-slot ids. Returns the translated
        feed (a shallow copy; untouched entries shared)."""
        self._check_attached()
        out = dict(feed)
        for p in plans:
            g = p.group
            g.apply(p, scope)
            for fname in g.feeds:
                if fname in out:
                    out[fname] = g.translate(np.asarray(out[fname]))
        return out

    def prepare_feed(self, feed, scope):
        """plan + apply in one synchronous call (the no-prefetch path)."""
        return self.apply(self.plan(feed), feed, scope)

    def flush(self, scope):
        """Write every resident row (and its optimizer state) back to the
        host cold store — call before checkpointing or reading
        :meth:`state_dict`."""
        self._check_attached()
        for g in self.groups:
            g.flush(scope)

    # -- checkpoint --------------------------------------------------------
    def state_dict(self, scope):
        """Flushed host stores + access counts + the residency map, keyed
        for np.savez. Residency IS training state: with a stateful update
        rule (momentum/adam, lazy semantics) resident-but-unused rows keep
        evolving on device, so an exact resume must re-pin the same rows
        to the same slots."""
        self.flush(scope)
        out = {}
        for g in self.groups:
            out[f"{g.name}::counts"] = g.counts.copy()
            out[f"{g.name}::row_of"] = g.row_of.copy()
            for t in g.table_names:
                out[f"{g.name}::host::{t}"] = g.host[t].copy()
                for aname, _fill in g.accums.get(t, ()):
                    out[f"{g.name}::host::{aname}"] = g.host[aname].copy()
        return out

    def delta_row_oracles(self, consumer=None):
        """Row oracles for tiered checkpointing, keyed by the
        :meth:`state_dict` host-store names: ``oracle(last_mark) ->
        (dirty_rows, new_mark)`` backed by each group's write-back tick
        — a delta save then carries only the host rows written back
        since the last published save instead of the full ``[V, ...]``
        stores (``fleet.AsyncCheckpointer(row_oracles=...)``). With
        ``last_mark=None`` (no published base yet) rows is None, which
        tells the checkpointer to store the array in full.

        `consumer` names an independent group-side cursor ("checkpoint",
        "publish", ...): with it, an ``oracle(None)`` falls back to the
        consumer's last COMMITTED mark (:meth:`commit_row_marks`) instead
        of "no base", so two delta chains — a checkpoint save landing
        between two model publishes, say — each see every row dirtied
        since their OWN last payload; without per-consumer cursors one
        chain's publish would silently swallow the other's rows."""

        def _make(group):
            def oracle(last_mark):
                mark = group.delta_tick()
                last = last_mark
                if last is None and consumer is not None:
                    last = group.consumer_mark(consumer)
                if last is None:
                    return None, mark
                return group.dirty_rows_since(last), mark

            return oracle

        out = {}
        for g in self.groups:
            oracle = _make(g)
            for t in g.table_names:
                out[f"{g.name}::host::{t}"] = oracle
                for aname, _fill in g.accums.get(t, ()):
                    out[f"{g.name}::host::{aname}"] = oracle
        return out

    def commit_row_marks(self, consumer, marks):
        """Durably advance `consumer`'s cursors after its payload
        committed. `marks` is the ``{oracle key: mark}`` dict built from
        the oracles' returned marks; keys map back to groups by their
        ``{group}::host::`` prefix."""
        for g in self.groups:
            prefix = f"{g.name}::host::"
            group_marks = [
                m for k, m in marks.items() if k.startswith(prefix)
            ]
            if group_marks:
                g.commit_consumer_mark(consumer, max(group_marks))

    def load_state_dict(self, state, scope):
        """Restore :meth:`state_dict` output. The hot-tier DEVICE arrays
        are ordinary persistables restored by the checkpoint load
        (io.load_persistables) — call this AFTER it; this call re-pins the
        saved slot map over them (flush() made host and device agree for
        resident rows, so either source is bitwise-correct)."""
        self._check_attached()
        for g in self.groups:
            g.counts[:] = state[f"{g.name}::counts"]
            for t in list(g.host):
                key = f"{g.name}::host::{t}"
                if key in state:
                    g.host[t][:] = state[key]
            row_of = state.get(f"{g.name}::row_of")
            if row_of is None:
                g.reset_residency()
            else:
                g.restore_residency(np.asarray(row_of), scope)

    def _check_attached(self):
        if not self._attached:
            from ..errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                "EmbeddingEngine is not attached; run the startup program "
                "and call engine.attach(scope) first"
            )
