"""Multi-process launcher: `python -m paddle_tpu.distributed.launch ...`.

Reference: python/paddle/distributed/launch.py:193-227 — builds the cluster
model from --cluster_node_ips / PaddleCloud env, spawns one process per GPU
with PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS, and supervises the children
(utils.py watch_local_trainers: abort the pod when a child dies).

TPU-native changes:
  * the process unit is a HOST, not an accelerator: one JAX process drives
    all local chips, so --nproc_per_node defaults to 1 and exists mainly
    for localhost simulation (reference test_dist_base.py:506 pattern);
  * rank 0's endpoint doubles as the JAX coordination-service address
    (PADDLE_COORDINATOR), replacing the reference's gen_nccl_id RPC server;
  * when simulating several processes on one host, children are forced onto
    the CPU platform with gloo cross-process collectives — a real pod sets
    neither and each host claims its TPU chips.
"""

from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys
import time

# full-jitter source for restart backoff: same-tick deaths draw
# independent delays instead of thundering back in lockstep (seedable in
# tests for determinism)
_restart_rng = random.Random()


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--node_ip", type=str, default="127.0.0.1",
                   help="this host's ip")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 for real TPU hosts; >1 "
                        "simulates a cluster on localhost over CPU)")
    p.add_argument("--simulate_cpu", action="store_true",
                   help="force children onto the CPU platform with gloo "
                        "collectives (localhost cluster simulation)")
    p.add_argument("--elastic", action="store_true",
                   help="restart dead children with bounded exponential "
                        "backoff instead of aborting the pod (rank 0 dying "
                        "still aborts: it owns the coordination service)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="per-rank restart budget under --elastic")
    p.add_argument("--restart_backoff", type=float, default=0.5,
                   help="base seconds for the restart backoff "
                        "(doubles per restart of that rank, capped at 10s, "
                        "full jitter so same-tick deaths respawn staggered)")
    p.add_argument("--heartbeat_dir", type=str, default=None,
                   help="directory of per-rank hb_rank{K} liveness files; "
                        "exported to children as PADDLE_HEARTBEAT_DIR so "
                        "TrainGuard/Heartbeat auto-beat once per step")
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="seconds without a heartbeat before a child is "
                        "declared HUNG and SIGTERM→SIGKILLed (then routed "
                        "through the --elastic restart path); 0 disables")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_cluster(args):
    ips = [ip for ip in args.cluster_node_ips.split(",") if ip]
    endpoints = []
    for ip in ips:
        for i in range(args.nproc_per_node):
            endpoints.append(f"{ip}:{args.started_port + i}")
    if args.node_ip not in ips:
        raise ValueError(
            f"--node_ip {args.node_ip} not in --cluster_node_ips {ips}"
        )
    node_idx = ips.index(args.node_ip)
    local_ranks = [
        node_idx * args.nproc_per_node + i for i in range(args.nproc_per_node)
    ]
    return endpoints, local_ranks


def _terminate_pod(procs, grace=10.0):
    """SIGTERM everyone, reap with a deadline, escalate to SIGKILL — a child
    blocked in a native collective often defers SIGTERM forever and would
    otherwise be orphaned holding its port. (Implementation shared with the
    serving process fleet: resilience/supervisor.py.)"""
    from ..resilience.supervisor import terminate_children

    terminate_children(procs, grace=grace)


def spawn_trainer(args, endpoints, rank, attempt=0):
    """Start (or restart) the trainer process for `rank`. Restarts append
    to the same per-rank log file so the crash that triggered the restart
    stays readable."""
    env = dict(os.environ)
    env.update(
        PADDLE_TRAINER_ID=str(rank),
        PADDLE_TRAINERS_NUM=str(len(endpoints)),
        PADDLE_TRAINER_ENDPOINTS=",".join(endpoints),
        PADDLE_CURRENT_ENDPOINT=endpoints[rank],
        PADDLE_COORDINATOR=endpoints[0],
        PADDLE_RESTART_ATTEMPT=str(attempt),
    )
    if args.simulate_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    if getattr(args, "heartbeat_dir", None):
        env["PADDLE_HEARTBEAT_DIR"] = args.heartbeat_dir
        if getattr(args, "heartbeat_timeout", 0):
            env["PADDLE_HEARTBEAT_TIMEOUT"] = str(args.heartbeat_timeout)
    cmd = [sys.executable, args.training_script] + args.training_script_args
    # fresh spawn truncates; a restart appends so the crash that triggered
    # it stays readable in the same per-rank log
    out = (
        open(
            os.path.join(args.log_dir, f"worker_{rank}.log"),
            "w" if attempt == 0 else "a",
        )
        if args.log_dir
        else None
    )
    proc = subprocess.Popen(cmd, env=env, stdout=out, stderr=out)
    proc._paddle_log = out
    proc._paddle_rank = rank
    # wall clock: heartbeat staleness compares against beat files written
    # by another process, and a fresh spawn must reset the stall baseline
    # even when a pre-kill beat file is still lying around
    proc._paddle_spawned = time.time()
    return proc


def start_local_trainers(args, endpoints, local_ranks):
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    if getattr(args, "heartbeat_dir", None):
        os.makedirs(args.heartbeat_dir, exist_ok=True)
    return [spawn_trainer(args, endpoints, rank) for rank in local_ranks]


def _beat_staleness(args, proc, now_wall):
    """Seconds since `proc`'s rank last proved liveness: its newest beat
    file if one postdates the spawn, else the spawn itself (a rank hung
    BEFORE its first beat — e.g. a stuck init collective — must still
    trip the watchdog; size --heartbeat_timeout above worst-case
    compile+warmup)."""
    from ..resilience.health import heartbeat_path, read_beat

    ref = getattr(proc, "_paddle_spawned", now_wall)
    beat = read_beat(
        heartbeat_path(args.heartbeat_dir, getattr(proc, "_paddle_rank", 0))
    )
    if beat is not None:
        try:
            ref = max(ref, float(beat.get("time", ref)))
        except (TypeError, ValueError):
            pass
    return now_wall - ref


def _kill_hung(proc, grace=5.0):
    """SIGTERM a hung child, escalating to SIGKILL after `grace` without
    blocking the supervision scan (shared: resilience/supervisor.py)."""
    from ..resilience.supervisor import kill_hung

    kill_hung(proc, grace=grace)


def watch_local_trainers(procs, args=None, endpoints=None):
    """Supervise the pod (reference utils.py watch_local_trainers /
    launch.py:219-226). Default policy: any child failure aborts the pod.
    Under ``--elastic``: a failed non-rank-0 child is restarted with
    bounded, full-jittered exponential backoff up to ``--max_restarts``
    times per rank — each dead rank gets its own independent deadline, so
    two ranks dying in the same poll tick neither share a slot nor
    respawn in lockstep. Rank 0 dying always aborts immediately (it hosts
    the JAX coordination service, so its death already doomed every peer).

    Liveness: with ``--heartbeat_dir``/``--heartbeat_timeout`` a child
    whose newest beat (or spawn, if it never beat) is older than the
    timeout is declared HUNG, SIGTERM→SIGKILLed (``resilience.hangs``),
    and its eventual death is handled exactly like a crash — i.e. routed
    through the elastic restart path.

    Preemption: a child exiting with the distinguished
    ``PREEMPTION_EXIT_CODE`` (it drained after SIGTERM and wrote a final
    checkpoint) is a CLEAN exit — no pod abort, no restart-budget burn —
    unless the launcher itself killed it as hung.

    The scan/backoff/stale-beat loop itself lives in
    ``resilience.supervisor.Supervisor`` (shared with the serving process
    fleet); this function contributes the launcher policy — rank 0 and
    non-elastic deaths abort the pod, preemption exits are clean, and the
    historical log lines/counters stay byte-identical."""
    from ..resilience.health import PREEMPTION_EXIT_CODE
    from ..resilience.supervisor import Supervisor

    elastic = bool(args and getattr(args, "elastic", False))
    max_restarts = getattr(args, "max_restarts", 3) if args else 3
    backoff_base = getattr(args, "restart_backoff", 0.5) if args else 0.5
    hb_timeout = float(getattr(args, "heartbeat_timeout", 0) or 0) if args else 0
    hb_dir = getattr(args, "heartbeat_dir", None) if args else None
    watch_beats = bool(hb_dir and hb_timeout > 0)
    ranks = {i: getattr(p, "_paddle_rank", i) for i, p in enumerate(procs)}
    sup = Supervisor(
        # late-bound module lookup: tests monkeypatch launch.spawn_trainer
        # to steer restarts, and that must keep working
        spawn=lambda i, attempt: spawn_trainer(
            args, endpoints, ranks[i], attempt
        ),
        max_restarts=max_restarts,
        backoff_base=backoff_base,
        backoff_cap=10.0,
        staleness=(
            (lambda p, now_wall: _beat_staleness(args, p, now_wall))
            if watch_beats else None
        ),
        stale_after=hb_timeout if watch_beats else 0.0,
        clean_exit=lambda rc, hung: (
            rc == 0 or (rc == PREEMPTION_EXIT_CODE and not hung)
        ),
        restartable=lambda i, rc, hung: elastic and ranks[i] != 0,
        rng=_restart_rng,
    )
    for i, p in enumerate(procs):
        sup.adopt(i, p)
    try:
        while True:
            for ev in sup.poll():
                i, p, kind = ev["key"], ev["proc"], ev["kind"]
                rank = ranks[i]
                if kind == "hung":
                    print(
                        f"[launch] rank {rank} (pid {p.pid}) hung: "
                        f"no heartbeat in {hb_timeout}s; killing",
                        file=sys.stderr,
                    )
                    from .. import observability as _obs

                    _obs.add("resilience.hangs")
                    _obs.add("resilience.hangs.launcher")
                elif kind == "respawned":
                    # mirror into the caller's list: _terminate_pod on a
                    # later abort must see the live child, not the corpse
                    procs[i] = p
                elif kind == "restart_scheduled":
                    print(
                        f"[launch --elastic] rank {rank} "
                        + ("hung (killed)" if ev["hung"]
                           else f"died (rc={ev['rc']})")
                        + f"; restart {ev['attempt']}/{max_restarts} "
                        f"in {ev['delay']:.1f}s",
                        file=sys.stderr,
                    )
                elif kind == "fatal":
                    n = ev["restarts"]
                    _terminate_pod(procs)
                    raise RuntimeError(
                        f"trainer rank {rank} (pid {p.pid}) "
                        + ("hung (heartbeat stale) and was killed, exit "
                           if ev["hung"] else "exited with ")
                        + f"code {ev['rc']}"
                        + (f" after {n} restart(s)" if elastic and n else "")
                        + "; pod aborted"
                    )
            if not sup.some_active():
                _terminate_pod(procs)  # reaps + closes log handles
                return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        _terminate_pod(procs)
        raise


def launch(argv=None):
    args = parse_args(argv)
    endpoints, local_ranks = build_cluster(args)
    procs = start_local_trainers(args, endpoints, local_ranks)
    return watch_local_trainers(procs, args, endpoints)


if __name__ == "__main__":
    sys.exit(launch())
