"""Multi-process launcher: `python -m paddle_tpu.distributed.launch ...`.

Reference: python/paddle/distributed/launch.py:193-227 — builds the cluster
model from --cluster_node_ips / PaddleCloud env, spawns one process per GPU
with PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS, and supervises the children
(utils.py watch_local_trainers: abort the pod when a child dies).

TPU-native changes:
  * the process unit is a HOST, not an accelerator: one JAX process drives
    all local chips, so --nproc_per_node defaults to 1 and exists mainly
    for localhost simulation (reference test_dist_base.py:506 pattern);
  * rank 0's endpoint doubles as the JAX coordination-service address
    (PADDLE_COORDINATOR), replacing the reference's gen_nccl_id RPC server;
  * when simulating several processes on one host, children are forced onto
    the CPU platform with gloo cross-process collectives — a real pod sets
    neither and each host claims its TPU chips.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--node_ip", type=str, default="127.0.0.1",
                   help="this host's ip")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 for real TPU hosts; >1 "
                        "simulates a cluster on localhost over CPU)")
    p.add_argument("--simulate_cpu", action="store_true",
                   help="force children onto the CPU platform with gloo "
                        "collectives (localhost cluster simulation)")
    p.add_argument("--elastic", action="store_true",
                   help="restart dead children with bounded exponential "
                        "backoff instead of aborting the pod (rank 0 dying "
                        "still aborts: it owns the coordination service)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="per-rank restart budget under --elastic")
    p.add_argument("--restart_backoff", type=float, default=0.5,
                   help="base seconds for the restart backoff "
                        "(doubles per restart of that rank, capped at 10s)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def build_cluster(args):
    ips = [ip for ip in args.cluster_node_ips.split(",") if ip]
    endpoints = []
    for ip in ips:
        for i in range(args.nproc_per_node):
            endpoints.append(f"{ip}:{args.started_port + i}")
    if args.node_ip not in ips:
        raise ValueError(
            f"--node_ip {args.node_ip} not in --cluster_node_ips {ips}"
        )
    node_idx = ips.index(args.node_ip)
    local_ranks = [
        node_idx * args.nproc_per_node + i for i in range(args.nproc_per_node)
    ]
    return endpoints, local_ranks


def _terminate_pod(procs, grace=10.0):
    """SIGTERM everyone, reap with a deadline, escalate to SIGKILL — a child
    blocked in a native collective often defers SIGTERM forever and would
    otherwise be orphaned holding its port."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    for p in procs:
        out = getattr(p, "_paddle_log", None)
        if out is not None:
            out.close()


def spawn_trainer(args, endpoints, rank, attempt=0):
    """Start (or restart) the trainer process for `rank`. Restarts append
    to the same per-rank log file so the crash that triggered the restart
    stays readable."""
    env = dict(os.environ)
    env.update(
        PADDLE_TRAINER_ID=str(rank),
        PADDLE_TRAINERS_NUM=str(len(endpoints)),
        PADDLE_TRAINER_ENDPOINTS=",".join(endpoints),
        PADDLE_CURRENT_ENDPOINT=endpoints[rank],
        PADDLE_COORDINATOR=endpoints[0],
        PADDLE_RESTART_ATTEMPT=str(attempt),
    )
    if args.simulate_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    cmd = [sys.executable, args.training_script] + args.training_script_args
    # fresh spawn truncates; a restart appends so the crash that triggered
    # it stays readable in the same per-rank log
    out = (
        open(
            os.path.join(args.log_dir, f"worker_{rank}.log"),
            "w" if attempt == 0 else "a",
        )
        if args.log_dir
        else None
    )
    proc = subprocess.Popen(cmd, env=env, stdout=out, stderr=out)
    proc._paddle_log = out
    proc._paddle_rank = rank
    return proc


def start_local_trainers(args, endpoints, local_ranks):
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    return [spawn_trainer(args, endpoints, rank) for rank in local_ranks]


def watch_local_trainers(procs, args=None, endpoints=None):
    """Supervise the pod (reference utils.py watch_local_trainers /
    launch.py:219-226). Default policy: any child failure aborts the pod.
    Under ``--elastic``: a failed non-rank-0 child is restarted with
    bounded exponential backoff up to ``--max_restarts`` times per rank;
    rank 0 dying always aborts immediately (it hosts the JAX coordination
    service, so its death already doomed every peer)."""
    elastic = bool(args and getattr(args, "elastic", False))
    max_restarts = getattr(args, "max_restarts", 3) if args else 3
    backoff_base = getattr(args, "restart_backoff", 0.5) if args else 0.5
    restarts = {}  # rank -> count
    pending = {}  # procs index -> monotonic time of the scheduled restart
    try:
        while True:
            alive = False
            now = time.monotonic()
            for i, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    alive = True
                    continue
                if rc == 0:
                    continue  # clean exit: done, never restarted
                if i in pending:
                    # backoff in progress: restart when its deadline
                    # arrives; never sleep inline — the scan must keep
                    # monitoring every other child (rank 0's death aborts
                    # immediately even mid-backoff)
                    alive = True
                    if now >= pending[i]:
                        del pending[i]
                        rank = getattr(p, "_paddle_rank", i)
                        log = getattr(p, "_paddle_log", None)
                        if log is not None:
                            log.close()
                        procs[i] = spawn_trainer(
                            args, endpoints, rank, restarts[rank]
                        )
                    continue
                rank = getattr(p, "_paddle_rank", i)
                n = restarts.get(rank, 0)
                if not elastic or rank == 0 or n >= max_restarts:
                    _terminate_pod(procs)
                    raise RuntimeError(
                        f"trainer rank {rank} (pid {p.pid}) exited with "
                        f"code {rc}"
                        + (f" after {n} restart(s)" if elastic and n else "")
                        + "; pod aborted"
                    )
                restarts[rank] = n + 1
                from ..resilience import backoff_delay

                delay = backoff_delay(n + 1, backoff_base, 10.0)
                print(
                    f"[launch --elastic] rank {rank} died (rc={rc}); "
                    f"restart {n + 1}/{max_restarts} in {delay:.1f}s",
                    file=sys.stderr,
                )
                pending[i] = now + delay
                alive = True
            if not alive:
                _terminate_pod(procs)  # reaps + closes log handles
                return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        _terminate_pod(procs)
        raise


def launch(argv=None):
    args = parse_args(argv)
    endpoints, local_ranks = build_cluster(args)
    procs = start_local_trainers(args, endpoints, local_ranks)
    return watch_local_trainers(procs, args, endpoints)


if __name__ == "__main__":
    sys.exit(launch())
