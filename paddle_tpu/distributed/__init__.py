from . import launch  # noqa: F401
